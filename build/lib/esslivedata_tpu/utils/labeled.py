"""Light labeled-array veneer over numpy/jax arrays.

The reference's workflow outputs and wire payloads are scipp DataArrays:
dims + coords (often bin edges) + units + masks (reference:
src/ess/livedata/kafka/scipp_da00_compat.py, workflows/detector_view/
providers.py:169-299). This module provides the minimal equivalent —
``Variable`` (values, dims, unit) and ``DataArray`` (data, coords, masks) —
with dim-name-aware broadcasting arithmetic, edge-aware slicing, and unit
conversion. Dense data only: event data never appears in this form (events
are fixed-shape device batches, see ops/event_batch.py).

Values may be numpy or jax arrays; arithmetic preserves the array namespace
of the left operand. ``.numpy`` materializes to host.
"""

from __future__ import annotations

from collections.abc import Mapping, Sequence
from typing import Any

import numpy as np

from .units import Unit, UnitError, unit as parse_unit

__all__ = ["Variable", "DataArray", "array", "scalar", "linspace", "midpoints", "concat"]


def _as_array(values: Any) -> Any:
    if isinstance(values, (list, tuple, int, float, bool, np.number)):
        return np.asarray(values)
    return values


class Variable:
    """An array with named dimensions and a physical unit."""

    __slots__ = ("_values", "_dims", "_unit")

    def __init__(
        self,
        values: Any,
        dims: Sequence[str] | None = None,
        unit: str | Unit | None = None,
    ) -> None:
        values = _as_array(values)
        if dims is None:
            if values.ndim != 0:
                raise ValueError("dims required for non-scalar Variable")
            dims = ()
        dims = tuple(dims)
        if len(dims) != values.ndim:
            raise ValueError(
                f"dims {dims} do not match array of ndim {values.ndim}"
            )
        self._values = values
        self._dims = dims
        self._unit = parse_unit(unit)

    # -- basic properties -------------------------------------------------
    @property
    def values(self) -> Any:
        return self._values

    @property
    def value(self) -> Any:
        """Scalar value (python object) — requires a 0-d variable."""
        if self._values.ndim != 0:
            raise ValueError("value only valid for 0-d Variable")
        return np.asarray(self._values)[()]

    @property
    def numpy(self) -> np.ndarray:
        return np.asarray(self._values)

    @property
    def dims(self) -> tuple[str, ...]:
        return self._dims

    @property
    def unit(self) -> Unit:
        return self._unit

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self._values.shape)

    @property
    def ndim(self) -> int:
        return self._values.ndim

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def sizes(self) -> dict[str, int]:
        return dict(zip(self._dims, self._values.shape, strict=True))

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of 0-d Variable")
        return self.shape[0]

    def __repr__(self) -> str:
        return (
            f"Variable(dims={self._dims}, shape={self.shape}, "
            f"unit={self._unit!r}, dtype={self.dtype})"
        )

    # -- conversion -------------------------------------------------------
    def to_unit(self, target: str | Unit) -> Variable:
        target = parse_unit(target)
        factor = self._unit.conversion_factor(target)
        if factor == 1.0:
            return Variable(self._values, self._dims, target)
        values = self._values * factor
        return Variable(values, self._dims, target)

    def astype(self, dtype) -> Variable:
        return Variable(self._values.astype(dtype), self._dims, self._unit)

    def copy(self) -> Variable:
        return Variable(np.array(self.numpy, copy=True), self._dims, self._unit)

    # -- slicing ----------------------------------------------------------
    def __getitem__(self, key) -> Variable:
        dim, idx = key
        axis = self._dims.index(dim)
        slicer: list[Any] = [slice(None)] * self.ndim
        slicer[axis] = idx
        values = self._values[tuple(slicer)]
        dims = (
            self._dims
            if isinstance(idx, slice)
            else self._dims[:axis] + self._dims[axis + 1 :]
        )
        return Variable(values, dims, self._unit)

    def transpose(self, dims: Sequence[str]) -> Variable:
        dims = tuple(dims)
        if set(dims) != set(self._dims):
            raise ValueError(f"transpose dims {dims} != {self._dims}")
        order = [self._dims.index(d) for d in dims]
        return Variable(self._values.transpose(order), dims, self._unit)

    # -- broadcasting arithmetic ------------------------------------------
    def _aligned(self, other: Variable) -> tuple[Any, Any, tuple[str, ...]]:
        out_dims = self._dims + tuple(d for d in other._dims if d not in self._dims)
        sizes = self.sizes
        for d, n in other.sizes.items():
            if d in sizes and sizes[d] != n:
                raise ValueError(f"Size mismatch along {d!r}: {sizes[d]} vs {n}")
            sizes[d] = n

        def align(v: Variable) -> Any:
            present = [d for d in out_dims if d in v._dims]
            vv = v.transpose(present)._values if present != list(v._dims) else v._values
            shape = tuple(sizes[d] if d in v._dims else 1 for d in out_dims)
            return vv.reshape(shape)

        return align(self), align(other), out_dims

    def _binop(self, other, op: str, unit_rule: str) -> Variable:
        if isinstance(other, (int, float, np.number)):
            other = Variable(np.asarray(other), (), self._unit if unit_rule == "same" else None)
        if not isinstance(other, Variable):
            return NotImplemented
        if unit_rule == "same":
            if not self._unit.compatible(other._unit):
                raise UnitError(f"Incompatible units: {self._unit} and {other._unit}")
            other = other.to_unit(self._unit)
            out_unit = self._unit
        elif unit_rule == "mul":
            out_unit = self._unit * other._unit
        elif unit_rule == "div":
            out_unit = self._unit / other._unit
        else:  # pragma: no cover
            raise AssertionError(unit_rule)
        a, b, dims = self._aligned(other)
        if op == "add":
            out = a + b
        elif op == "sub":
            out = a - b
        elif op == "mul":
            out = a * b
        elif op == "div":
            out = a / b
        else:  # pragma: no cover
            raise AssertionError(op)
        return Variable(out, dims, out_unit)

    def __add__(self, other) -> Variable:
        return self._binop(other, "add", "same")

    def __sub__(self, other) -> Variable:
        return self._binop(other, "sub", "same")

    def __mul__(self, other) -> Variable:
        return self._binop(other, "mul", "mul")

    def __truediv__(self, other) -> Variable:
        return self._binop(other, "div", "div")

    def __radd__(self, other) -> Variable:
        return self._binop(other, "add", "same")

    def __rmul__(self, other) -> Variable:
        return self._binop(other, "mul", "mul")

    def __rsub__(self, other) -> Variable:
        return (-self)._binop(other, "add", "same")

    def __rtruediv__(self, other) -> Variable:
        if isinstance(other, (int, float, np.number)):
            other = Variable(np.asarray(other), (), None)
        if not isinstance(other, Variable):
            return NotImplemented
        return other._binop(self, "div", "div")

    def __neg__(self) -> Variable:
        return Variable(-self._values, self._dims, self._unit)

    def __iadd__(self, other) -> Variable:
        out = self._binop(other, "add", "same")
        if out.dims != self._dims:
            raise ValueError("in-place add must not broadcast new dims")
        self._values = out._values
        return self

    # -- reductions -------------------------------------------------------
    def sum(self, dim: str | None = None) -> Variable:
        if dim is None:
            return Variable(self._values.sum(), (), self._unit)
        axis = self._dims.index(dim)
        dims = self._dims[:axis] + self._dims[axis + 1 :]
        return Variable(self._values.sum(axis=axis), dims, self._unit)

    def max(self) -> Variable:
        return Variable(self._values.max(), (), self._unit)

    def min(self) -> Variable:
        return Variable(self._values.min(), (), self._unit)

    def allclose(self, other: Variable, rtol: float = 1e-6, atol: float = 0.0) -> bool:
        if self._dims != other._dims or not self._unit.compatible(other._unit):
            return False
        o = other.to_unit(self._unit)
        return bool(
            np.allclose(self.numpy, o.numpy, rtol=rtol, atol=atol)
            if self.shape == o.shape
            else False
        )

    def identical(self, other: Variable) -> bool:
        return (
            self._dims == other._dims
            and self._unit == other._unit
            and self.shape == other.shape
            and bool(np.array_equal(self.numpy, other.numpy))
        )


class DataArray:
    """Data variable + coordinates + masks, scipp-DataArray-like.

    Coords may be bin edges: a coord of length N+1 along a data dim of
    length N is treated as edges by slicing and concatenation.
    """

    __slots__ = ("data", "coords", "masks", "name")

    def __init__(
        self,
        data: Variable,
        coords: Mapping[str, Variable] | None = None,
        masks: Mapping[str, Variable] | None = None,
        name: str = "",
    ) -> None:
        self.data = data
        self.coords: dict[str, Variable] = dict(coords or {})
        self.masks: dict[str, Variable] = dict(masks or {})
        self.name = name

    # -- properties -------------------------------------------------------
    @property
    def dims(self) -> tuple[str, ...]:
        return self.data.dims

    @property
    def shape(self) -> tuple[int, ...]:
        return self.data.shape

    @property
    def sizes(self) -> dict[str, int]:
        return self.data.sizes

    @property
    def unit(self) -> Unit:
        return self.data.unit

    @property
    def values(self) -> Any:
        return self.data.values

    def __repr__(self) -> str:
        return (
            f"DataArray(name={self.name!r}, dims={self.dims}, shape={self.shape}, "
            f"unit={self.unit!r}, coords={list(self.coords)}, masks={list(self.masks)})"
        )

    def is_edges(self, coord: str, dim: str | None = None) -> bool:
        c = self.coords[coord]
        dim = dim or (c.dims[-1] if c.dims else coord)
        if dim not in c.dims or dim not in self.dims:
            return False
        return c.sizes[dim] == self.sizes[dim] + 1

    # -- slicing ----------------------------------------------------------
    def __getitem__(self, key) -> DataArray:
        dim, idx = key
        data = self.data[dim, idx]
        coords = {}
        for cname, c in self.coords.items():
            if dim in c.dims:
                if isinstance(idx, slice) and self.is_edges(cname, dim):
                    start, stop, step = idx.indices(self.sizes[dim])
                    if step != 1:
                        raise ValueError("strided slicing of edge coords unsupported")
                    coords[cname] = c[dim, start : stop + 1]
                else:
                    coords[cname] = c[dim, idx]
            else:
                coords[cname] = c
        masks = {
            mname: (m[dim, idx] if dim in m.dims else m)
            for mname, m in self.masks.items()
        }
        return DataArray(data, coords, masks, self.name)

    # -- arithmetic -------------------------------------------------------
    def _binop(self, other, op) -> DataArray:
        if isinstance(other, DataArray):
            # Coords shared by both operands must agree — adding counts from
            # histograms with different bin edges is scientifically wrong and
            # must fail loudly (the reference relies on scipp for this).
            for cname in self.coords.keys() & other.coords.keys():
                if not self.coords[cname].identical(other.coords[cname]):
                    raise ValueError(
                        f"Mismatched coord {cname!r} in DataArray arithmetic"
                    )
            coords = dict(other.coords)
            coords.update(self.coords)
            rhs = other.data
            masks = dict(other.masks)
            masks.update(self.masks)
        else:
            coords = dict(self.coords)
            rhs = other
            masks = dict(self.masks)
        data = getattr(self.data, op)(rhs)
        return DataArray(data, coords, masks, self.name)

    def __add__(self, other) -> DataArray:
        return self._binop(other, "__add__")

    def __sub__(self, other) -> DataArray:
        return self._binop(other, "__sub__")

    def __mul__(self, other) -> DataArray:
        return self._binop(other, "__mul__")

    def __truediv__(self, other) -> DataArray:
        return self._binop(other, "__truediv__")

    def __iadd__(self, other) -> DataArray:
        rhs = other.data if isinstance(other, DataArray) else other
        self.data += rhs
        return self

    def sum(self, dim: str | None = None) -> DataArray:
        if dim is None:
            return DataArray(self.data.sum(), {}, {}, self.name)
        coords = {
            cname: c for cname, c in self.coords.items() if dim not in c.dims
        }
        masks = {m: v for m, v in self.masks.items() if dim not in v.dims}
        return DataArray(self.data.sum(dim), coords, masks, self.name)

    def to_unit(self, target) -> DataArray:
        return DataArray(self.data.to_unit(target), self.coords, self.masks, self.name)

    def copy(self) -> DataArray:
        return DataArray(
            self.data.copy(),
            {k: v.copy() for k, v in self.coords.items()},
            {k: v.copy() for k, v in self.masks.items()},
            self.name,
        )

    def same_structure(self, other: DataArray) -> bool:
        """True when dims/shape/unit/coords match — the reference uses this to
        decide accumulate-vs-restart (accumulators.py:238-261)."""
        if not isinstance(other, DataArray):
            return False
        if self.dims != other.dims or self.shape != other.shape:
            return False
        if not self.unit.compatible(other.unit):
            return False
        if set(self.coords) != set(other.coords):
            return False
        return all(
            self.coords[c].identical(other.coords[c]) for c in self.coords
        )


# -- constructors ---------------------------------------------------------


def array(
    values: Any,
    dims: Sequence[str],
    unit: str | Unit | None = None,
    coords: Mapping[str, Variable] | None = None,
    name: str = "",
) -> DataArray:
    return DataArray(Variable(values, dims, unit), coords, name=name)


def scalar(value: Any, unit: str | Unit | None = None) -> Variable:
    return Variable(np.asarray(value), (), unit)


def linspace(
    dim: str, start: float, stop: float, num: int, unit: str | Unit | None = None
) -> Variable:
    return Variable(np.linspace(start, stop, num), (dim,), unit)


def midpoints(var: Variable, dim: str | None = None) -> Variable:
    dim = dim or var.dims[-1]
    axis = var.dims.index(dim)
    sl_lo: list[Any] = [slice(None)] * var.ndim
    sl_hi: list[Any] = [slice(None)] * var.ndim
    sl_lo[axis] = slice(None, -1)
    sl_hi[axis] = slice(1, None)
    vals = 0.5 * (var.values[tuple(sl_lo)] + var.values[tuple(sl_hi)])
    return Variable(vals, var.dims, var.unit)


def concat(arrays: Sequence[DataArray], dim: str) -> DataArray:
    """Concatenate along ``dim``; edge coords are merged (shared boundary)."""
    if not arrays:
        raise ValueError("concat of empty sequence")
    first = arrays[0]
    axis = first.dims.index(dim)
    data_vals = np.concatenate([np.asarray(a.data.values) for a in arrays], axis=axis)
    data = Variable(data_vals, first.dims, first.unit)
    coords: dict[str, Variable] = {}
    for cname, c in first.coords.items():
        if dim not in c.dims:
            coords[cname] = c
            continue
        caxis = c.dims.index(dim)
        if first.is_edges(cname, dim):
            pieces = [np.asarray(arrays[0].coords[cname].values)]
            for a in arrays[1:]:
                nxt = np.asarray(a.coords[cname].values)
                pieces.append(np.take(nxt, np.arange(1, nxt.shape[caxis]), axis=caxis))
            coords[cname] = Variable(np.concatenate(pieces, axis=caxis), c.dims, c.unit)
        else:
            coords[cname] = Variable(
                np.concatenate(
                    [np.asarray(a.coords[cname].values) for a in arrays], axis=caxis
                ),
                c.dims,
                c.unit,
            )
    masks: dict[str, Variable] = {}
    for mname, m in first.masks.items():
        if dim in m.dims:
            maxis = m.dims.index(dim)
            masks[mname] = Variable(
                np.concatenate(
                    [np.asarray(a.masks[mname].values) for a in arrays], axis=maxis
                ),
                m.dims,
                m.unit,
            )
        else:
            masks[mname] = m
    return DataArray(data, coords, masks, first.name)

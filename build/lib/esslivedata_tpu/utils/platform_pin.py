"""Pin JAX to the CPU backend before any backend initialization.

The ambient environment may pin JAX to a real accelerator platform via a
sitecustomize hook that overrides JAX_PLATFORMS after env parsing; when the
accelerator relay is unreachable, backend init then hangs indefinitely.
Setting the env var alone is therefore not enough — jax.config must be
updated directly, before anything touches a backend.

Single source of truth for the workaround used by tests/conftest.py,
__graft_entry__.py, and bench.py.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def pin_cpu(n_devices: int | None = None) -> None:
    """Force the CPU backend, with at least ``n_devices`` virtual devices.

    Safe to call repeatedly; must first be called before JAX initializes a
    backend (later calls are no-ops in effect). An existing device-count
    flag in XLA_FLAGS is raised to ``n_devices`` if it is lower — never
    lowered.
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
        if m is None:
            flags = f"{flags} {_COUNT_FLAG}={n_devices}".strip()
        elif int(m.group(1)) < n_devices:
            flags = flags.replace(m.group(0), f"{_COUNT_FLAG}={n_devices}")
        os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

"""Minimal physical-unit algebra.

The reference leans on scipp's C++ unit system (e.g. unit-checked Timestamp
arithmetic, reference: src/ess/livedata/core/timestamp.py:169-190, and da00
variables carrying units on the wire). We only need a small, fast subset:
parse the unit strings that occur in neutron live-data (time, length, angle,
energy, counts, frequency), multiply/divide/power them, and compute scale
factors for conversions. Implemented as a frozen dataclass over a dimension
exponent vector + scale; no external dependencies.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from fractions import Fraction
from functools import lru_cache

__all__ = ["Unit", "UnitError", "unit"]


class UnitError(ValueError):
    """Raised on incompatible-unit operations or unparseable unit strings."""


# Base dimensions. 'count' is its own dimension (like scipp's counts) so that
# e.g. counts + meters is an error and counts/s is a rate.
_DIMS = ("time", "length", "mass", "angle", "count", "temperature", "current")

_Vec = tuple[Fraction, ...]
_ZERO: _Vec = tuple(Fraction(0) for _ in _DIMS)


def _vec(**exps: int) -> _Vec:
    return tuple(Fraction(exps.get(d, 0)) for d in _DIMS)


@dataclass(frozen=True, slots=True)
class Unit:
    """A physical unit: scale factor to coherent base units + dimension vector."""

    scale: float
    dims: _Vec
    _name: str | None = None

    # -- algebra ---------------------------------------------------------
    def __mul__(self, other: Unit) -> Unit:
        if not isinstance(other, Unit):
            return NotImplemented
        return Unit(
            self.scale * other.scale,
            tuple(a + b for a, b in zip(self.dims, other.dims, strict=True)),
        )

    def __truediv__(self, other: Unit) -> Unit:
        if not isinstance(other, Unit):
            return NotImplemented
        return Unit(
            self.scale / other.scale,
            tuple(a - b for a, b in zip(self.dims, other.dims, strict=True)),
        )

    def __pow__(self, exp: int | float | Fraction) -> Unit:
        f = Fraction(exp).limit_denominator(1000)
        return Unit(self.scale ** float(f), tuple(d * f for d in self.dims))

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Unit):
            return NotImplemented
        return self.dims == other.dims and math.isclose(
            self.scale, other.scale, rel_tol=1e-12
        )

    def __hash__(self) -> int:
        return hash((round(math.log10(self.scale), 9) if self.scale > 0 else 0, self.dims))

    # -- conversions -----------------------------------------------------
    @property
    def is_dimensionless(self) -> bool:
        return self.dims == _ZERO

    def compatible(self, other: Unit) -> bool:
        return self.dims == other.dims

    def conversion_factor(self, to: Unit) -> float:
        """Multiplicative factor converting values in ``self`` to ``to``."""
        if self.dims != to.dims:
            raise UnitError(f"Incompatible units: {self} -> {to}")
        return self.scale / to.scale

    # -- repr ------------------------------------------------------------
    def __repr__(self) -> str:
        return self._name or self._derived_name()

    def _derived_name(self) -> str:
        # Find a registered atomic name with identical scale+dims.
        for name, u in _REGISTRY.items():
            if u.dims == self.dims and math.isclose(u.scale, self.scale, rel_tol=1e-12):
                return name
        # Well-known compound spellings (kept parseable for wire round trip).
        for spec in _REPR_ALIASES:
            u = unit(spec)
            if u.dims == self.dims and math.isclose(
                u.scale, self.scale, rel_tol=1e-12
            ):
                return spec
        num, den = [], []
        for d, e in zip(_DIMS, self.dims, strict=True):
            if e == 0:
                continue
            base = _BASE_NAME[d]
            part = base if abs(e) == 1 else f"{base}**{abs(e)}"
            (num if e > 0 else den).append(part)
        s = "*".join(num) or "1"
        if den:
            s += "/" + "/".join(den)
        if not math.isclose(self.scale, 1.0, rel_tol=1e-12):
            s = f"{self.scale:g}*{s}"
        return s


_BASE_NAME = {
    "time": "s",
    "length": "m",
    "mass": "kg",
    "angle": "rad",
    "count": "counts",
    "temperature": "K",
    "current": "A",
}

_DEG = math.pi / 180.0
_EV = 1.602176634e-19  # J

_REGISTRY: dict[str, Unit] = {}


def _register(name: str, scale: float, **exps: int) -> None:
    _REGISTRY[name] = Unit(scale, _vec(**exps), name)


# Dimensionless
_register("dimensionless", 1.0)
_register("one", 1.0)
_register("", 1.0)
_register("%", 0.01)
# Counts
_register("counts", 1.0, count=1)
_register("count", 1.0, count=1)
# Time
_register("s", 1.0, time=1)
_register("ms", 1e-3, time=1)
_register("us", 1e-6, time=1)
_register("µs", 1e-6, time=1)
_register("ns", 1e-9, time=1)
_register("ps", 1e-12, time=1)
_register("min", 60.0, time=1)
_register("h", 3600.0, time=1)
# Frequency
_register("Hz", 1.0, time=-1)
_register("kHz", 1e3, time=-1)
_register("MHz", 1e6, time=-1)
# Length
_register("m", 1.0, length=1)
_register("cm", 1e-2, length=1)
_register("mm", 1e-3, length=1)
_register("um", 1e-6, length=1)
_register("nm", 1e-9, length=1)
_register("angstrom", 1e-10, length=1)
_register("Angstrom", 1e-10, length=1)
_register("Å", 1e-10, length=1)
# Mass
_register("kg", 1.0, mass=1)
_register("g", 1e-3, mass=1)
# Angle
_register("rad", 1.0, angle=1)
_register("deg", _DEG, angle=1)
# Temperature (scale-only; no offset support — fine for kelvin streams)
_register("K", 1.0, temperature=1)
# Energy: J = kg m^2 / s^2
_register("J", 1.0, mass=1, length=2, time=-2)
_register("eV", _EV, mass=1, length=2, time=-2)
_register("meV", _EV * 1e-3, mass=1, length=2, time=-2)
_register("ueV", _EV * 1e-6, mass=1, length=2, time=-2)
# Current / voltage-ish extras occasionally seen in f144 logs
_register("A", 1.0, current=1)
_register("mA", 1e-3, current=1)
_register("V", 1.0, mass=1, length=2, time=-3, current=-1)
_register("mV", 1e-3, mass=1, length=2, time=-3, current=-1)
_register("T", 1.0, mass=1, time=-2, current=-1)
_register("bar", 1e5, mass=1, length=-1, time=-2)
_register("mbar", 1e2, mass=1, length=-1, time=-2)
_register("W", 1.0, mass=1, length=2, time=-3)
_register("MW", 1e6, mass=1, length=2, time=-3)


_REPR_ALIASES = (
    "1/angstrom",
    "1/nm",
    "1/m",
    "counts/s",
    "m/s",
    "mm/s",
    "deg/s",
    "rad/s",
)


def _parse_token(tok: str) -> Unit:
    tok = tok.strip()
    exp = 1
    for sep in ("**", "^"):
        if sep in tok:
            tok, e = tok.split(sep, 1)
            tok = tok.strip()
            try:
                exp = int(e.strip())
            except ValueError as err:
                raise UnitError(f"Bad exponent in unit token {tok!r}{sep}{e!r}") from err
            break
    if tok in ("1", ""):
        return _REGISTRY["dimensionless"]
    try:
        u = _REGISTRY[tok]
    except KeyError as err:
        raise UnitError(f"Unknown unit {tok!r}") from err
    return u if exp == 1 else u**exp


@lru_cache(maxsize=1024)
def unit(spec: str | Unit | None) -> Unit:
    """Parse a unit string like ``'us'``, ``'counts'``, ``'m/s'``, ``'1/angstrom'``.

    Grammar: ``tok ('*' tok)* ('/' tok)*`` with per-token ``**n`` exponents.
    ``None`` and ``''`` parse as dimensionless.
    """
    if isinstance(spec, Unit):
        return spec
    if spec is None:
        return _REGISTRY["dimensionless"]
    s = spec.strip()
    if s in _REGISTRY:
        return _REGISTRY[s]
    # Normalize '**' to '^' so splitting on '*' means multiplication only.
    s = s.replace("**", "^")
    parts = s.split("/")
    result = _REGISTRY["dimensionless"]
    for mult in parts[0].split("*"):
        if mult.strip():
            result = result * _parse_token(mult)
    for div in parts[1:]:
        for i, mult in enumerate(div.split("*")):
            if mult.strip():
                tok = _parse_token(mult)
                result = result / tok if i == 0 else result * tok
    return result

"""Labeled arrays, units, and small shared utilities.

This is the build's replacement for the slice of scipp's C++ array layer that
the reference actually uses on the wire and in workflow outputs: labeled
dims, coords, units, arithmetic, and slicing (reference:
src/ess/livedata/preprocessors/accumulators.py, kafka/scipp_da00_compat.py).
Event data ("binned" arrays in scipp) intentionally has NO equivalent here —
events are staged as fixed-shape device batches instead (see ops/).
"""

from .units import Unit, UnitError, unit
from .labeled import DataArray, Variable, array, linspace, midpoints, scalar

__all__ = [
    "DataArray",
    "Unit",
    "UnitError",
    "Variable",
    "array",
    "linspace",
    "midpoints",
    "scalar",
    "unit",
]

"""Device-mesh parallelism: sharded histogramming and collective reductions.

The reference scales out with OS processes partitioned by Kafka topic
(SURVEY.md section 2.10) and has no collective backend at all; compute-level
scale-out here is TPU-native instead: a ``jax.sharding.Mesh`` with a
``data`` axis (event-stream shards, the DP analog) and a ``bank`` axis
(bin-space shards over detector banks/screen rows — the TP/SP analog for a
histogramming workload, cf. SURVEY.md section 5 "long-context" note), with
XLA collectives (psum) riding ICI for cross-shard merges and
monitor/detector normalization. Kafka over DCN remains the inter-host
system bus, unchanged.
"""

from .mesh import make_mesh
from .sharded_hist import ShardedHistogrammer
from .sharded_qhist import ShardedQHistogrammer

__all__ = ["ShardedHistogrammer", "ShardedQHistogrammer", "make_mesh"]

"""Mesh construction helpers."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh"]


def make_mesh(
    n_devices: int | None = None,
    *,
    data: int | None = None,
    bank: int | None = None,
    devices=None,
) -> Mesh:
    """Build a 2-D ('data', 'bank') mesh over the first ``n_devices`` devices.

    ``data`` shards the event stream (DP analog); ``bank`` shards bin space
    (TP/SP analog). If only one of data/bank is given the other is inferred;
    if neither, devices all go to ``bank`` (bin-space sharding is the
    memory-relieving axis, which is the usual reason to shard).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if len(devices) < n_devices:
        raise ValueError(
            f"Requested {n_devices} devices, only {len(devices)} available"
        )
    if data is None and bank is None:
        data, bank = 1, n_devices
    elif data is None:
        if n_devices % bank:
            raise ValueError(f"{n_devices} devices not divisible by bank={bank}")
        data = n_devices // bank
    elif bank is None:
        if n_devices % data:
            raise ValueError(f"{n_devices} devices not divisible by data={data}")
        bank = n_devices // data
    if data * bank != n_devices:
        raise ValueError(f"data*bank = {data * bank} != n_devices = {n_devices}")
    arr = np.asarray(devices).reshape(data, bank)
    return Mesh(arr, ("data", "bank"))

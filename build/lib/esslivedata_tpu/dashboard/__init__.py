"""Live web dashboard: data plane, orchestration, plotting, web UI.

Parity with reference ``src/ess/livedata/dashboard/`` (SURVEY.md section
2.7) with the same architecture decisions — single-writer ingestion with
keys-only notifications and pull-based extraction (ADR 0007), frame-gated
session flushes (ADR 0005), job adoption from heartbeats (ADR 0008), and an
in-process fake backend that makes the full UI work without Kafka (the dev
demo + test rig). The widget substrate differs by necessity: the reference
renders Panel/HoloViews; this build renders matplotlib to PNG behind a
small tornado app with a polling HTML front end (Panel is not available in
this environment, and the dashboard is the cold path — the architecture,
not the widget toolkit, is what carries over).
"""

"""NICOS derived-device overview (reference: dashboard/derived_devices.py).

Backend services republish contracted workflow outputs on the stable
NICOS device topic (ADR 0006, core/nicos_devices.py); this registry tracks
the latest value per device name so the dashboard (and NICOS-facing
tooling) sees a flat name->value table with staleness, independent of
which job produced it.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any

__all__ = ["DerivedDevice", "DerivedDeviceRegistry"]

STALE_AFTER_S = 30.0


@dataclass
class DerivedDevice:
    name: str
    value: Any
    unit: str = ""
    timestamp_ns: int = 0
    last_seen_wall: float = 0.0

    @property
    def is_stale(self) -> bool:
        return time.monotonic() - self.last_seen_wall > STALE_AFTER_S


class DerivedDeviceRegistry:
    def __init__(self) -> None:
        self._devices: dict[str, DerivedDevice] = {}
        self._lock = threading.Lock()

    def on_device_value(
        self, name: str, value: Any, *, unit: str = "", timestamp_ns: int = 0
    ) -> None:
        with self._lock:
            self._devices[name] = DerivedDevice(
                name=name,
                value=value,
                unit=unit,
                timestamp_ns=timestamp_ns,
                last_seen_wall=time.monotonic(),
            )

    def devices(self) -> list[DerivedDevice]:
        with self._lock:
            return sorted(self._devices.values(), key=lambda d: d.name)

    def get(self, name: str) -> DerivedDevice | None:
        with self._lock:
            return self._devices.get(name)

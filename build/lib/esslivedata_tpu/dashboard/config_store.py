"""Persisted UI state (reference: dashboard/config_store.py, 758 LoC).

Namespaced key->JSON-document stores; the file-backed store survives
dashboard restarts (grid layouts, staged workflow params, plot configs —
reference tests/integration/config_persistence_test.py), the in-memory
store backs tests and ephemeral sessions. Writes are atomic
(write-to-temp + rename) so a crash mid-save never corrupts state.
"""

from __future__ import annotations

import json
import logging
import threading
from pathlib import Path
from typing import Any, Protocol

__all__ = [
    "ConfigStore",
    "ConfigStoreManager",
    "FileConfigStore",
    "MemoryConfigStore",
]

logger = logging.getLogger(__name__)


class ConfigStore(Protocol):
    def load(self, key: str) -> dict[str, Any] | None: ...

    def save(self, key: str, value: dict[str, Any]) -> None: ...

    def delete(self, key: str) -> None: ...

    def keys(self) -> list[str]: ...


class MemoryConfigStore:
    def __init__(self) -> None:
        self._data: dict[str, dict[str, Any]] = {}
        self._lock = threading.Lock()

    def load(self, key: str) -> dict[str, Any] | None:
        with self._lock:
            value = self._data.get(key)
            return json.loads(json.dumps(value)) if value is not None else None

    def save(self, key: str, value: dict[str, Any]) -> None:
        with self._lock:
            self._data[key] = json.loads(json.dumps(value))

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._data)


class FileConfigStore:
    """One JSON file per key under ``root``.

    Filenames are sanitized for the filesystem, but the *original* key is
    persisted inside the document (``__key__`` envelope) so ``keys()``
    returns exact keys after a restart and two distinct keys that sanitize
    identically are detected as a collision rather than silently
    clobbering each other.
    """

    def __init__(self, root: str | Path) -> None:
        self._root = Path(root)
        self._root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, key: str) -> Path:
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in key)
        if not safe:
            raise ValueError(f"Config key {key!r} sanitizes to empty")
        return self._root / f"{safe}.json"

    def _read(
        self, path: Path
    ) -> tuple[str, dict[str, Any], bool] | None:
        """(key, doc, legacy). Legacy = pre-envelope file: its original key
        is unknown, the sanitized stem is the best available name."""
        try:
            envelope = json.loads(path.read_text())
        except FileNotFoundError:
            return None
        except json.JSONDecodeError:
            logger.warning("Corrupt config file %s ignored", path)
            return None
        if (
            isinstance(envelope, dict)
            and "__key__" in envelope
            and "doc" in envelope
        ):
            return envelope["__key__"], envelope["doc"], False
        if isinstance(envelope, dict):
            return path.stem, envelope, True
        logger.warning("Corrupt config file %s ignored", path)
        return None

    def load(self, key: str) -> dict[str, Any] | None:
        with self._lock:
            entry = self._read(self._path(key))
            if entry is None:
                return None
            stored_key, doc, legacy = entry
            # A legacy file matches any key that sanitizes onto it (its
            # true key is unknowable), an enveloped file only its own.
            return doc if legacy or stored_key == key else None

    def save(self, key: str, value: dict[str, Any]) -> None:
        path = self._path(key)
        with self._lock:
            existing = self._read(path)
            if (
                existing is not None
                and not existing[2]  # legacy files are overwritable
                and existing[0] != key
            ):
                raise ValueError(
                    f"Config keys {existing[0]!r} and {key!r} collide on "
                    f"file {path.name}"
                )
            tmp = path.with_suffix(".json.tmp")
            tmp.write_text(
                json.dumps(
                    {"__key__": key, "doc": value}, indent=2, sort_keys=True
                )
            )
            tmp.replace(path)

    def delete(self, key: str) -> None:
        with self._lock:
            path = self._path(key)
            entry = self._read(path)
            # Unlink unless the file verifiably belongs to a *different*
            # key — corrupt and legacy files must stay deletable.
            if entry is None or entry[2] or entry[0] == key:
                path.unlink(missing_ok=True)

    def keys(self) -> list[str]:
        with self._lock:
            out = []
            for path in self._root.glob("*.json"):
                entry = self._read(path)
                if entry is not None:
                    out.append(entry[0])
            return sorted(out)


class ConfigStoreManager:
    """Namespaced access onto one backing store (grids/, workflows/, ...)."""

    def __init__(self, store: ConfigStore) -> None:
        self._store = store

    def namespaced(self, namespace: str) -> "_NamespacedStore":
        return _NamespacedStore(self._store, namespace)


class _NamespacedStore:
    def __init__(self, store: ConfigStore, namespace: str) -> None:
        self._store = store
        self._prefix = f"{namespace}__"

    def load(self, key: str) -> dict[str, Any] | None:
        return self._store.load(self._prefix + key)

    def save(self, key: str, value: dict[str, Any]) -> None:
        self._store.save(self._prefix + key, value)

    def delete(self, key: str) -> None:
        self._store.delete(self._prefix + key)

    def keys(self) -> list[str]:
        return [
            k[len(self._prefix):]
            for k in self._store.keys()
            if k.startswith(self._prefix)
        ]

"""Workflow lifecycle from the dashboard side.

Parity with reference ``dashboard/job_orchestrator.py`` (1367 LoC) at the
architectural level: staged-config -> commit two-phase start (stage params,
then commit publishes the command), job numbers generated dashboard-side,
stop/remove/reset commands, ROI pushes, reconciliation with heartbeats via
JobService (adoption is handled there).
"""

from __future__ import annotations

import uuid
from typing import Any

from ..config.workflow_spec import JobId, WorkflowConfig, WorkflowId
from ..workflows.workflow_factory import WorkflowFactory, workflow_registry
from .job_service import JobService, PendingCommand
from .transport import Transport

__all__ = ["JobOrchestrator"]


class JobOrchestrator:
    def __init__(
        self,
        *,
        transport: Transport,
        job_service: JobService,
        registry: WorkflowFactory | None = None,
    ) -> None:
        self._transport = transport
        self._job_service = job_service
        self._registry = registry if registry is not None else workflow_registry
        self._staged: dict[tuple[str, str], dict[str, Any]] = {}

    # -- two-phase start ---------------------------------------------------
    def stage(
        self, workflow_id: WorkflowId, source_name: str, params: dict[str, Any]
    ) -> None:
        """Stage params for (workflow, source); validated against the spec
        immediately so the UI gets early feedback."""
        spec = self._registry[workflow_id]
        spec.validate_params(params)
        self._staged[(str(workflow_id), source_name)] = params

    def staged_params(
        self, workflow_id: WorkflowId, source_name: str
    ) -> dict[str, Any] | None:
        return self._staged.get((str(workflow_id), source_name))

    def commit(
        self,
        workflow_id: WorkflowId,
        source_name: str,
        *,
        aux_source_names: dict[str, str] | None = None,
    ) -> tuple[JobId, PendingCommand]:
        """Publish the start command with a fresh job number."""
        params = self._staged.pop((str(workflow_id), source_name), {})
        job_id = JobId(source_name=source_name, job_number=uuid.uuid4())
        config = WorkflowConfig(
            identifier=workflow_id,
            job_id=job_id,
            params=params,
            aux_source_names=aux_source_names or {},
        )
        self._transport.publish_command(
            {"kind": "start_job", "config": config.model_dump(mode="json")}
        )
        pending = self._job_service.track_command(
            source_name, job_id.job_number, "start_job"
        )
        return job_id, pending

    def start(
        self,
        workflow_id: WorkflowId,
        source_name: str,
        params: dict[str, Any] | None = None,
    ) -> tuple[JobId, PendingCommand]:
        """stage+commit in one call (programmatic use)."""
        self.stage(workflow_id, source_name, params or {})
        return self.commit(workflow_id, source_name)

    # -- lifecycle commands ------------------------------------------------
    def _job_command(self, action: str, job_id: JobId) -> PendingCommand:
        self._transport.publish_command(
            {
                "kind": "job_command",
                "action": action,
                "source_name": job_id.source_name,
                "job_number": str(job_id.job_number),
            }
        )
        return self._job_service.track_command(
            job_id.source_name, job_id.job_number, action
        )

    def stop(self, job_id: JobId) -> PendingCommand:
        return self._job_command("stop", job_id)

    def remove(self, job_id: JobId) -> PendingCommand:
        return self._job_command("remove", job_id)

    def reset(self, job_id: JobId) -> PendingCommand:
        return self._job_command("reset", job_id)

    def set_rois(self, job_id: JobId, rois: dict[str, Any]) -> PendingCommand:
        """Publish ROI definitions for a running detector-view job (the ROI
        round trip, reference roi_request_plots)."""
        self._transport.publish_command(
            {
                "kind": "roi_update",
                "source_name": job_id.source_name,
                "job_number": str(job_id.job_number),
                "rois": rois,
            }
        )
        return self._job_service.track_command(
            job_id.source_name, job_id.job_number, "roi_update"
        )

    # -- catalog -----------------------------------------------------------
    def available_workflows(self, instrument: str):
        return self._registry.specs_for_instrument(instrument)

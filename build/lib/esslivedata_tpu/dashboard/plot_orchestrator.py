"""Plot grids/cells lifecycle + persistence.

Parity with reference ``dashboard/plot_orchestrator.py`` (1 798 LoC) at the
architectural level: the orchestrator owns the set of plot grids, each a
named arrangement of cells; a cell selects result streams by
(workflow, output, source) pattern and renders via the plotter registry.
Cells match ResultKeys as data arrives (keys-only notifications from
DataService); matches commit the owning grid's FrameClock generation so
sessions repaint exactly the grids whose data moved (ADR 0005).
Grid configurations persist across restarts through a ConfigStore and can
be seeded from per-instrument YAML templates (config/grid_template.py).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field, replace

from ..config.grid_template import (
    CellGeometry,
    GridCellSpec,
    GridSpec,
    load_grid_templates,
)
from ..config.workflow_spec import ResultKey
from .config_store import ConfigStore, MemoryConfigStore
from .data_service import DataService, DataSubscription
from .frame_clock import FrameClock

__all__ = ["PlotCell", "PlotGrid", "PlotOrchestrator"]

logger = logging.getLogger(__name__)


@dataclass
class PlotCell:
    """A live cell: its spec plus the ResultKeys currently matched to it."""

    spec: GridCellSpec
    keys: set[ResultKey] = field(default_factory=set)

    def matches(self, key: ResultKey) -> bool:
        s = self.spec
        if s.workflow and str(key.workflow_id) != s.workflow:
            return False
        if s.output and key.output_name != s.output:
            return False
        if s.source and key.job_id.source_name != s.source:
            return False
        return bool(s.workflow or s.output or s.source)

    @property
    def wants_history(self) -> bool:
        """True when this cell's configured extractor aggregates over the
        key's past values — the data service must then retain history for
        the cell's keys (pull path has no subscription to announce it).
        Derived from the extractor itself so a new history-wanting
        extractor cannot silently miss the buffer upgrade."""
        from .plots import PlotParams

        try:
            extractor = PlotParams.from_dict(
                dict(self.spec.params or {})
            ).make_extractor()
        except (ValueError, TypeError):
            # Corrupt persisted params must not take the orchestrator
            # down during _restore; the render path 400s them instead.
            return False
        return extractor is not None and extractor.wants_history


@dataclass
class PlotGrid:
    grid_id: str
    spec: GridSpec
    cells: list[PlotCell] = field(default_factory=list)


class PlotOrchestrator:
    """Owns grids; binds cells to data; drives the frame clock."""

    def __init__(
        self,
        *,
        data_service: DataService,
        frame_clock: FrameClock | None = None,
        store: ConfigStore | None = None,
        instrument: str = "",
    ) -> None:
        self._data = data_service
        self.clock = frame_clock or FrameClock()
        self._store = store or MemoryConfigStore()
        self._instrument = instrument
        self._grids: dict[str, PlotGrid] = {}
        self._lock = threading.RLock()
        self._subscription = DataSubscription(
            keys=set(), on_updated=self._on_data
        )
        data_service.subscribe(self._subscription)
        self._restore()
        if not self._grids and instrument:
            self._seed_from_templates(instrument)

    # -- persistence --------------------------------------------------------
    def _restore(self) -> None:
        for grid_id in self._store.keys():
            raw = self._store.load(grid_id)
            if raw is None:
                continue
            try:
                spec = GridSpec.from_dict(raw)
            except Exception:
                logger.exception("Corrupt grid config %r ignored", grid_id)
                continue
            self._install(grid_id, spec, persist=False)

    def _seed_from_templates(self, instrument: str) -> None:
        for spec in load_grid_templates(instrument):
            if spec.enabled:
                self._install(spec.name, spec, persist=True)

    def _persist(self, grid: PlotGrid) -> None:
        spec = grid.spec
        self._store.save(
            grid.grid_id,
            {
                "name": spec.name,
                "title": spec.title,
                "description": spec.description,
                "nrows": spec.nrows,
                "ncols": spec.ncols,
                "enabled": spec.enabled,
                "cells": [
                    {
                        "geometry": {
                            "row": c.spec.geometry.row,
                            "col": c.spec.geometry.col,
                            "row_span": c.spec.geometry.row_span,
                            "col_span": c.spec.geometry.col_span,
                        },
                        "workflow": c.spec.workflow,
                        "output": c.spec.output,
                        "source": c.spec.source,
                        "plotter": c.spec.plotter,
                        "title": c.spec.title,
                        "params": c.spec.params_dict,
                    }
                    for c in grid.cells
                ],
            },
        )

    # -- grid lifecycle ------------------------------------------------------
    def _install(self, grid_id: str, spec: GridSpec, *, persist: bool) -> PlotGrid:
        grid = PlotGrid(
            grid_id=grid_id,
            spec=spec,
            cells=[PlotCell(spec=c) for c in spec.cells],
        )
        with self._lock:
            self._grids[grid_id] = grid
            # Bind any already-present data.
            for key in self._data.keys():
                for cell in grid.cells:
                    if cell.matches(key):
                        cell.keys.add(key)
        for cell in grid.cells:
            self._sync_history_demand(cell)
        if persist:
            self._persist(grid)
        self.clock.commit(grid_id)
        return grid

    def add_grid(self, spec: GridSpec, grid_id: str | None = None) -> PlotGrid:
        return self._install(grid_id or spec.name, spec, persist=True)

    def remove_grid(self, grid_id: str) -> None:
        with self._lock:
            self._grids.pop(grid_id, None)
        self._store.delete(grid_id)

    def add_cell(self, grid_id: str, cell_spec: GridCellSpec) -> PlotCell:
        with self._lock:
            grid = self._grids[grid_id]
            cell = PlotCell(spec=cell_spec)
            for key in self._data.keys():
                if cell.matches(key):
                    cell.keys.add(key)
            grid.cells.append(cell)
            grid.spec = replace(
                grid.spec, cells=(*grid.spec.cells, cell_spec)
            )
        self._sync_history_demand(cell)
        self._persist(grid)
        self.clock.commit(grid_id)
        return cell

    def remove_cell(self, grid_id: str, index: int) -> None:
        with self._lock:
            grid = self._grids[grid_id]
            del grid.cells[index]
            cells = list(grid.spec.cells)
            del cells[index]
            grid.spec = replace(grid.spec, cells=tuple(cells))
        self._persist(grid)
        self.clock.commit(grid_id)

    def update_cell(
        self, grid_id: str, index: int, **changes
    ) -> PlotCell:
        """Edit a cell's spec in place (the plot-config surface): stream
        selection, plotter choice, title, presentation params. Selection
        changes rebind the cell's matched keys; everything persists."""
        from ..config.grid_template import GridCellSpec

        if "params" in changes and isinstance(changes["params"], dict):
            changes["params"] = GridCellSpec.freeze_params(changes["params"])
        with self._lock:
            grid = self._grids[grid_id]
            cell = grid.cells[index]
            new_spec = replace(cell.spec, **changes)
            new_cell = PlotCell(spec=new_spec)
            for key in self._data.keys():
                if new_cell.matches(key):
                    new_cell.keys.add(key)
            grid.cells[index] = new_cell
            cells = list(grid.spec.cells)
            cells[index] = new_spec
            grid.spec = replace(grid.spec, cells=tuple(cells))
        self._sync_history_demand(new_cell)
        self._persist(grid)
        self.clock.commit(grid_id)
        return new_cell

    def _sync_history_demand(self, cell: PlotCell) -> None:
        """Upgrade the buffers of a history-wanting cell's keys.

        The render pull path carries no subscription, so demand is
        announced here — at every point a cell gains keys or its config
        changes. Idempotent; never downgrades (another consumer may still
        want the history).
        """
        if not cell.wants_history:
            return
        with self._lock:
            keys = set(cell.keys)
        for key in keys:
            self._data.require_history(key)

    # -- data binding --------------------------------------------------------
    def _on_data(self, keys: set[ResultKey]) -> None:
        """Ingestion-side: match new keys to cells, commit touched grids."""
        touched: set[str] = set()
        bound: list[PlotCell] = []
        with self._lock:
            for grid in self._grids.values():
                for cell in grid.cells:
                    for key in keys:
                        if key in cell.keys or cell.matches(key):
                            if key not in cell.keys:
                                cell.keys.add(key)
                                bound.append(cell)
                            touched.add(grid.grid_id)
        for cell in bound:
            self._sync_history_demand(cell)
        for grid_id in touched:
            self.clock.commit(grid_id)

    # -- views ---------------------------------------------------------------
    def grids(self) -> list[PlotGrid]:
        with self._lock:
            return list(self._grids.values())

    def grid(self, grid_id: str) -> PlotGrid | None:
        with self._lock:
            return self._grids.get(grid_id)

    def snapshot(self) -> list[dict]:
        """Plain-data view for other threads (HTTP handlers): cells' live
        key sets are copied under the lock — the ingestion thread mutates
        them concurrently and iterating them unlocked races."""
        with self._lock:
            return [
                {
                    "grid_id": grid.grid_id,
                    "title": grid.spec.title,
                    "nrows": grid.spec.nrows,
                    "ncols": grid.spec.ncols,
                    "generation": self.clock.grid_generation(grid.grid_id),
                    "cells": [
                        {
                            "index": i,
                            "geometry": {
                                "row": c.spec.geometry.row,
                                "col": c.spec.geometry.col,
                                "row_span": c.spec.geometry.row_span,
                                "col_span": c.spec.geometry.col_span,
                            },
                            "title": c.spec.title,
                            "workflow": c.spec.workflow,
                            "output": c.spec.output,
                            "source": c.spec.source,
                            "plotter": c.spec.plotter,
                            "params": c.spec.params_dict,
                            "keys": sorted(
                                c.keys, key=lambda k: k.to_string()
                            ),
                        }
                        for i, c in enumerate(grid.cells)
                    ],
                }
                for grid in self._grids.values()
            ]


def default_cell(workflow: str = "", output: str = "", source: str = "") -> GridCellSpec:
    """Convenience for tests/UI: a 1x1 cell at the next free slot (0,0)."""
    return GridCellSpec(
        geometry=CellGeometry(row=0, col=0),
        workflow=workflow,
        output=output,
        source=source,
    )

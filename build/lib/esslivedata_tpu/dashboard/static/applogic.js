// Pure client-side logic — no DOM, no fetch, no globals. Everything here
// is a plain (args) -> value function so the DOM-wiring layer (app.js)
// stays thin. Schema interpretation lives SERVER-side (formspec.py,
// pytest-covered); this file keeps only what must run in the browser:
// per-kind input coercion and plot-pixel <-> data-coordinate transforms.
// Structural contracts (endpoint references, brace balance, no inline-JS
// residue) are pinned by tests/dashboard/static_assets_test.py.
'use strict';
const AppLogic = {
  // Raw input string -> typed param value per formspec kind.
  // undefined = omit the field (server default applies).
  coerceFieldValue(kind, raw, checked) {
    if (kind === 'boolean') return !!checked;
    if (raw === '' || raw === undefined || raw === null) return undefined;
    if (kind === 'integer' || kind === 'number') return Number(raw);
    if (kind === 'text') return raw;  // never JSON.parse: '123' stays text
    try { return JSON.parse(raw); } catch (e) { return raw; }
  },

  // Collect a params object from [{name, kind}] + a raw-value lookup.
  collectParams(fields, rawOf) {
    const params = {};
    for (const f of fields) {
      const {raw, checked} = rawOf(f.name);
      const v = AppLogic.coerceFieldValue(f.kind, raw, checked);
      if (v !== undefined) params[f.name] = v;
    }
    return params;
  },

  // PNG pixel <-> data coordinates via the plot meta (axes_px box +
  // xlim/ylim). PNG rows grow downward.
  pxToData(meta, px, py) {
    const a = meta.axes_px;
    const fx = (px - a.x0) / (a.x1 - a.x0);
    const fy = (a.y1 - py) / (a.y1 - a.y0);
    return [meta.xlim[0] + fx * (meta.xlim[1] - meta.xlim[0]),
            meta.ylim[0] + fy * (meta.ylim[1] - meta.ylim[0])];
  },
  dataToPx(meta, x, y) {
    const a = meta.axes_px;
    const fx = (x - meta.xlim[0]) / (meta.xlim[1] - meta.xlim[0]);
    const fy = (y - meta.ylim[0]) / (meta.ylim[1] - meta.ylim[0]);
    return [a.x0 + fx * (a.x1 - a.x0), a.y1 - fy * (a.y1 - a.y0)];
  },

  // Widen a degenerate [lo, hi] range (freeze of a constant image must
  // keep vmin < vmax server-side).
  span(lo, hi) { return hi > lo ? [lo, hi] : [lo - 0.5, lo + 0.5]; },
};

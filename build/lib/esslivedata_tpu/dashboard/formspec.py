"""Schema -> wizard form-field derivation, server-side.

The workflow wizard used to derive input kinds from the raw JSON schema
in browser JS — logic that was untestable here (no JS runtime in the
environment). The derivation now lives in Python where pytest covers
it; the client renders the precomputed field list mechanically and only
keeps a per-kind raw->value coercion (static/applogic.js).

Reference parity: this is the TPU-repo answer to the reference's
pydantic-model-driven parameter widgets
(dashboard/widgets/configuration_widget.py:1 builds Panel widgets from
the params model; here the server ships a widget-agnostic spec).
"""

from __future__ import annotations

import json
from typing import Any

__all__ = ["schema_to_formspec"]


def _resolve_ref(schema: dict, root: dict) -> dict:
    """Follow a local $ref (pydantic emits '#/$defs/Name')."""
    ref = schema.get("$ref")
    if not ref or not ref.startswith("#/"):
        return schema
    node: Any = root
    for part in ref[2:].split("/"):
        node = node.get(part, {}) if isinstance(node, dict) else {}
    return node if isinstance(node, dict) else {}


def _field_kind(prop: dict, root: dict) -> str:
    """'boolean' | 'integer' | 'number' | 'text' | 'json'.

    anyOf with null (Optional[...]) unwraps to the non-null variant;
    $ref / object / array land on 'json' (the input rides as a JSON
    string, the server parses).
    """
    if "$ref" in prop:
        prop = _resolve_ref(prop, root)
    variants = prop.get("anyOf")
    if variants:
        non_null = [v for v in variants if v.get("type") != "null"]
        if len(non_null) == 1:
            return _field_kind(non_null[0], root)
        return "json"
    t = prop.get("type")
    if t == "boolean":
        return "boolean"
    if t == "integer":
        return "integer"
    if t == "number":
        return "number"
    if t == "string":
        return "text"
    return "json"


def _default_text(prop: dict, kind: str) -> str | None:
    """The default rendered the way the matching input expects it."""
    if "default" not in prop:
        return None
    d = prop["default"]
    if d is None:
        return None
    if kind == "boolean":
        return "true" if d else "false"
    if isinstance(d, (dict, list)):
        return json.dumps(d)
    return str(d)


def schema_to_formspec(schema: dict | None) -> list[dict] | None:
    """Flat field descriptors for the wizard, or None without a model.

    Each entry: ``{name, kind, default_text, description, enum}`` —
    everything the client needs to build an input without touching the
    schema. ``enum`` (list of string options) is set for Literal/enum
    string fields so the client can render a select.
    """
    if not schema:
        return None
    fields = []
    for name, prop in (schema.get("properties") or {}).items():
        resolved = _resolve_ref(prop, schema) if "$ref" in prop else prop
        kind = _field_kind(prop, schema)
        enum = None
        enum_src = resolved
        if "anyOf" in resolved:
            non_null = [
                v for v in resolved["anyOf"] if v.get("type") != "null"
            ]
            if len(non_null) == 1:
                enum_src = non_null[0]
                if "$ref" in enum_src:
                    enum_src = _resolve_ref(enum_src, schema)
        if isinstance(enum_src.get("enum"), list) and all(
            isinstance(v, str) for v in enum_src["enum"]
        ):
            enum = list(enum_src["enum"])
            kind = "text"
        fields.append(
            {
                "name": name,
                "kind": kind,
                "default_text": _default_text(prop, kind),
                "description": prop.get("description")
                or resolved.get("description")
                or "",
                "enum": enum,
            }
        )
    return fields

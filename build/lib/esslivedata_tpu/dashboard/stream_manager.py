"""Binds result streams to consumer pipes (reference: dashboard/stream_manager.py).

A thin factory over DataService subscriptions: given a key-selection
predicate and an extractor, it creates a subscription whose callback
pushes extracted values into a consumer (a plot cell's pipe, a table, a
test probe). Owns its subscriptions so a departing session tears down
everything it wired.
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any

from ..config.workflow_spec import ResultKey
from .data_service import DataService, DataSubscription
from .extractors import Extractor, LatestValueExtractor

__all__ = ["StreamManager"]


class StreamManager:
    def __init__(self, *, data_service: DataService) -> None:
        self._data = data_service
        self._subscriptions: list[DataSubscription] = []

    def bind(
        self,
        keys: set[ResultKey],
        consumer: Callable[[ResultKey, Any], None],
        *,
        extractor: Extractor | None = None,
    ) -> DataSubscription:
        """On update of any key, extract its current value into ``consumer``."""
        extractor = extractor or LatestValueExtractor()

        def on_updated(updated: set[ResultKey]) -> None:
            for key in updated:
                value = self._data.get(key, extractor)
                if value is not None:
                    consumer(key, value)

        sub = DataSubscription(
            keys=keys, on_updated=on_updated, extractor=extractor
        )
        self._data.subscribe(sub)
        self._subscriptions.append(sub)
        return sub

    def unbind(self, subscription: DataSubscription) -> None:
        self._data.unsubscribe(subscription)
        if subscription in self._subscriptions:
            self._subscriptions.remove(subscription)

    def close(self) -> None:
        for sub in list(self._subscriptions):
            self.unbind(sub)

"""Frame-gated repaint clock (ADR 0005, reference dashboard/frame_clock.py).

The ingestion thread commits a *generation* per grid after writing a batch;
sessions poll at their own cadence and repaint a grid only when its
generation advanced since the last paint. This decouples ingest rate from
paint rate — a slow browser never backs up ingestion, a fast poller never
repaints unchanged grids.
"""

from __future__ import annotations

import threading

__all__ = ["FrameClock"]


class FrameClock:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._generations: dict[str, int] = {}
        self._global = 0

    def commit(self, grid_id: str) -> int:
        """Ingestion finished writing data visible in ``grid_id``."""
        with self._lock:
            self._global += 1
            self._generations[grid_id] = self._global
            return self._global

    def commit_all(self) -> int:
        """Data arrived that may affect every grid (e.g. unassigned keys)."""
        with self._lock:
            self._global += 1
            for grid_id in self._generations:
                self._generations[grid_id] = self._global
            return self._global

    @property
    def generation(self) -> int:
        with self._lock:
            return self._global

    def grid_generation(self, grid_id: str) -> int:
        with self._lock:
            return self._generations.get(grid_id, 0)

    def changed_since(self, grid_id: str, seen: int) -> bool:
        """Session-side check: repaint needed?"""
        return self.grid_generation(grid_id) > seen

"""User-facing notifications (reference: dashboard/notification_queue.py).

Producers (ingestion, orchestrators, command tracking) push; sessions
drain at their own pace with a per-session cursor, so one slow browser
never blocks another and late-joining sessions see recent history.
Bounded: old notifications fall off.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Literal

__all__ = ["Notification", "NotificationQueue"]

Level = Literal["info", "warning", "error"]


@dataclass(frozen=True)
class Notification:
    seq: int
    level: Level
    message: str
    created_wall: float = field(default_factory=time.time)


class NotificationQueue:
    def __init__(self, *, max_items: int = 200) -> None:
        self._items: list[Notification] = []
        self._seq = 0
        self._max = max_items
        self._lock = threading.Lock()

    def push(self, level: Level, message: str) -> Notification:
        with self._lock:
            self._seq += 1
            note = Notification(seq=self._seq, level=level, message=message)
            self._items.append(note)
            del self._items[: -self._max]
            return note

    def info(self, message: str) -> Notification:
        return self.push("info", message)

    def warning(self, message: str) -> Notification:
        return self.push("warning", message)

    def error(self, message: str) -> Notification:
        return self.push("error", message)

    def since(self, seq: int) -> list[Notification]:
        """All notifications newer than ``seq`` (the session cursor)."""
        with self._lock:
            return [n for n in self._items if n.seq > seq]

    @property
    def latest_seq(self) -> int:
        with self._lock:
            return self._seq

"""Per-key result buffers with bounded temporal history.

Parity with reference ``dashboard/temporal_buffers.py`` (SingleValueBuffer:
92, TemporalBuffer:304) + ``temporal_buffer_manager.py``: each ResultKey's
stream lands in a buffer; extractors decide whether history is retained.
``TemporalBuffer`` keeps a time-ordered deque under a size budget,
evicting oldest entries.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Protocol, runtime_checkable

from ..core.timestamp import Timestamp

__all__ = ["Buffer", "SingleValueBuffer", "TemporalBuffer", "TemporalBufferManager"]


@runtime_checkable
class Buffer(Protocol):
    def put(self, timestamp: Timestamp, value: Any) -> None: ...

    def latest(self) -> Any: ...

    def history(self) -> list[tuple[Timestamp, Any]]: ...

    def clear(self) -> None: ...


class SingleValueBuffer:
    """Keeps only the newest value — the default for image-sized results."""

    def __init__(self) -> None:
        self._value: Any = None
        self._timestamp: Timestamp | None = None

    def put(self, timestamp: Timestamp, value: Any) -> None:
        if self._timestamp is None or timestamp >= self._timestamp:
            self._value = value
            self._timestamp = timestamp

    @property
    def is_empty(self) -> bool:
        return self._timestamp is None

    def latest(self) -> Any:
        return self._value

    def history(self) -> list[tuple[Timestamp, Any]]:
        if self._timestamp is None:
            return []
        return [(self._timestamp, self._value)]

    def clear(self) -> None:
        self._value = None
        self._timestamp = None


def _nbytes(value: Any) -> int:
    values = getattr(value, "values", None)
    nbytes = getattr(values, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    return 64  # scalars / small objects


class TemporalBuffer:
    """Time-ordered history under a byte budget (drop-oldest)."""

    def __init__(self, max_bytes: int = 64 * 1024 * 1024) -> None:
        self._entries: deque[tuple[Timestamp, Any]] = deque()
        self._max_bytes = max_bytes
        self._bytes = 0

    def put(self, timestamp: Timestamp, value: Any) -> None:
        self._entries.append((timestamp, value))
        self._bytes += _nbytes(value)
        while self._bytes > self._max_bytes and len(self._entries) > 1:
            _, old = self._entries.popleft()
            self._bytes -= _nbytes(old)

    @property
    def is_empty(self) -> bool:
        return not self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def latest(self) -> Any:
        return self._entries[-1][1] if self._entries else None

    def history(self) -> list[tuple[Timestamp, Any]]:
        return list(self._entries)

    def window(self, duration_s: float) -> list[tuple[Timestamp, Any]]:
        if not self._entries:
            return []
        cutoff = self._entries[-1][0].ns - int(duration_s * 1e9)
        return [(t, v) for t, v in self._entries if t.ns >= cutoff]

    def clear(self) -> None:
        self._entries.clear()
        self._bytes = 0


class TemporalBufferManager:
    """Chooses/creates the buffer per key based on extractor demand
    (reference: temporal_buffer_manager.py): history is only retained for
    keys some extractor wants history for."""

    def __init__(self, *, history_max_bytes: int = 64 * 1024 * 1024) -> None:
        self._buffers: dict[Any, Buffer] = {}
        self._wants_history: set[Any] = set()
        self._history_max_bytes = history_max_bytes

    def require_history(self, key: Any) -> None:
        self._wants_history.add(key)
        existing = self._buffers.get(key)
        if isinstance(existing, SingleValueBuffer):
            upgraded = TemporalBuffer(self._history_max_bytes)
            for t, v in existing.history():
                upgraded.put(t, v)
            self._buffers[key] = upgraded

    def get(self, key: Any) -> Buffer | None:
        return self._buffers.get(key)

    def put(self, key: Any, timestamp: Timestamp, value: Any) -> None:
        buf = self._buffers.get(key)
        if buf is None:
            buf = (
                TemporalBuffer(self._history_max_bytes)
                if key in self._wants_history
                else SingleValueBuffer()
            )
            self._buffers[key] = buf
        buf.put(timestamp, value)

    def keys(self):
        return self._buffers.keys()

    def clear(self) -> None:
        self._buffers.clear()

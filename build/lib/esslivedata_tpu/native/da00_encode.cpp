// Native da00 serializer: the publish hot path.
//
// The dashboard-facing publish serializes ~10 outputs x ~4 variables per
// pulse; the Python flatbuffers Builder costs ~140us per variable, which
// made da00 encoding the single largest CPU cost of the ingest->publish
// latency path (~8ms of a ~15ms step at LOKI scale, round-4 profile).
// This is a minimal prepend-style flatbuffers writer specialized to the
// da00 layout pinned by schemas/da00_dataarray.fbs and the golden wire
// tests. It mirrors the Python builder's operation order and vtable
// deduplication so its output is byte-identical to kafka/wire.py's
// encode_da00 (asserted by tests/kafka/native_da00_test.py).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 da00_encode.cpp -o _da00.so
// (driven by native/__init__.py, same pattern as ingest.cpp).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Builder {
    uint8_t* buf;      // caller-provided scratch, filled from the END
    int64_t cap;
    int64_t head;      // index of first used byte (grows downward)
    int64_t minalign;
    bool overflow;
    // Offsets (from end) of previously written vtables for dedup.
    std::vector<int64_t> vtables;

    explicit Builder(uint8_t* out, int64_t out_cap)
        : buf(out), cap(out_cap), head(out_cap), minalign(1),
          overflow(false) {}

    int64_t offset() const { return cap - head; }

    void make_space(int64_t n) {
        if (head - n < 0) overflow = true;
    }

    void place_u8(uint8_t v) {
        make_space(1);
        if (overflow) return;
        buf[--head] = v;
    }

    void place_bytes(const uint8_t* p, int64_t n) {
        make_space(n);
        if (overflow) return;
        head -= n;
        std::memcpy(buf + head, p, n);
    }

    template <typename T>
    void place(T v) {
        make_space(sizeof(T));
        if (overflow) return;
        head -= sizeof(T);
        std::memcpy(buf + head, &v, sizeof(T));
    }

    // Pad so that after writing `additional` bytes the next write of
    // `size` bytes is aligned (python Builder.Prep).
    void prep(int64_t size, int64_t additional) {
        if (size > minalign) minalign = size;
        int64_t align_size =
            ((~(offset() + additional)) + 1) & (size - 1);
        make_space(align_size);
        if (overflow) return;
        for (int64_t i = 0; i < align_size; ++i) buf[--head] = 0;
    }

    template <typename T>
    void prepend(T v) {
        prep(sizeof(T), 0);
        place(v);
    }

    void prepend_uoffset(int64_t off) {
        prep(4, 0);
        place<uint32_t>(static_cast<uint32_t>(offset() - off + 4));
    }

    int64_t create_string(const uint8_t* s, int64_t n) {
        prep(4, n + 1);
        place_u8(0);
        place_bytes(s, n);
        place<uint32_t>(static_cast<uint32_t>(n));
        return offset();
    }

    void start_vector(int64_t elem_size, int64_t count, int64_t align) {
        prep(4, elem_size * count);
        prep(align, elem_size * count);
    }

    int64_t end_vector(int64_t count) {
        place<uint32_t>(static_cast<uint32_t>(count));
        return offset();
    }

};

// Table assembly state (python Builder.StartObject/EndObject).
struct TableWriter {
    Builder* b;
    int64_t slots[16];
    int n_slots;
    int64_t object_start;  // b->offset() at StartObject

    TableWriter(Builder* builder, int n) : b(builder), n_slots(n) {
        for (int i = 0; i < n; ++i) slots[i] = 0;
        object_start = b->offset();
    }

    void slot_uoffset(int i, int64_t off) {
        b->prepend_uoffset(off);
        slots[i] = b->offset();
    }

    void slot_i64(int i, int64_t v, int64_t def) {
        if (v == def) return;
        b->prepend<int64_t>(v);
        slots[i] = b->offset();
    }

    void slot_i8(int i, int8_t v, int8_t def) {
        if (v == def) return;
        b->prepend<int8_t>(v);
        slots[i] = b->offset();
    }

    int64_t end() {
        // soffset placeholder.
        b->prep(4, 0);
        b->place<int32_t>(0);
        int64_t object_offset = b->offset();
        // Trim trailing unused slots (python WriteVtable trims).
        int n = n_slots;
        while (n > 0 && slots[n - 1] == 0) --n;
        // Candidate vtable content.
        uint16_t vt[2 + 16];
        int64_t vt_len = (2 + n) * 2;
        vt[0] = static_cast<uint16_t>(vt_len);
        vt[1] = static_cast<uint16_t>(object_offset - object_start);
        for (int i = 0; i < n; ++i)
            vt[2 + i] = slots[i]
                            ? static_cast<uint16_t>(object_offset - slots[i])
                            : 0;
        // Dedup against previously written vtables (python
        // Builder.WriteVtable's VtableEqual scan).
        for (int64_t existing : b->vtables) {
            const uint8_t* evt = b->buf + (b->cap - existing);
            uint16_t elen;
            std::memcpy(&elen, evt, 2);
            if (elen != vt_len) continue;
            if (std::memcmp(evt, vt, vt_len) == 0) {
                // Reuse: point the soffset at the existing vtable.
                int32_t so = static_cast<int32_t>(existing - object_offset);
                std::memcpy(b->buf + (b->cap - object_offset), &so, 4);
                return object_offset;
            }
        }
        // Write a fresh vtable (fields prepended in reverse).
        for (int i = n - 1; i >= 0; --i)
            b->prepend<uint16_t>(vt[2 + i]);
        b->prepend<uint16_t>(vt[1]);
        b->prepend<uint16_t>(vt[0]);
        int64_t vtable_offset = b->offset();
        int32_t so = static_cast<int32_t>(vtable_offset - object_offset);
        if (!b->overflow)
            std::memcpy(b->buf + (b->cap - object_offset), &so, 4);
        b->vtables.push_back(vtable_offset);
        return object_offset;
    }
};

}  // namespace

extern "C" {

// Returns bytes written (from the FRONT of out), or -1 on overflow
// (caller retries with a bigger buffer), or -2 on invalid input.
//
// String table: strings_blob with n_strs+1 offsets; indices reference
// it. label_idx/source_idx entries of -1 omit the slot.
int64_t ld_da00_encode(
    const uint8_t* strings_blob, const int64_t* str_offs, int32_t n_strs,
    int32_t source_name_idx, int64_t timestamp, int32_t n_vars,
    const int32_t* name_idx, const int32_t* unit_idx,
    const int32_t* label_idx, const int32_t* source_idx,
    const int8_t* dtype_codes,
    const int32_t* axes_start, const int32_t* axes_count,
    const int32_t* axes_idx_flat,
    const int32_t* dims_start, const int32_t* dims_count,
    const int64_t* shapes_flat,
    const int64_t* data_offs, const uint8_t* data_blob,
    uint8_t* out, int64_t out_cap) {
    if (n_vars < 0 || source_name_idx < 0 || source_name_idx >= n_strs)
        return -2;
    Builder b(out, out_cap);

    auto str_ptr = [&](int32_t idx) {
        return strings_blob + str_offs[idx];
    };
    auto str_len = [&](int32_t idx) {
        return str_offs[idx + 1] - str_offs[idx];
    };

    std::vector<int64_t> var_offs(static_cast<size_t>(n_vars));
    for (int32_t i = 0; i < n_vars; ++i) {
        // Mirror _encode_da00_variable's write order exactly.
        int64_t data_len = data_offs[i + 1] - data_offs[i];
        int64_t data_off;
        if (data_len == 0) {
            b.start_vector(1, 0, 1);
            data_off = b.end_vector(0);
        } else {
            b.prep(4, data_len);
            b.prep(1, data_len);
            b.place_bytes(data_blob + data_offs[i], data_len);
            data_off = b.end_vector(data_len);
        }
        int64_t shape_off = 0;
        int32_t nd = dims_count[i];
        if (nd > 0) {
            b.start_vector(8, nd, 8);
            const int64_t* dims = shapes_flat + dims_start[i];
            for (int32_t d = nd - 1; d >= 0; --d) b.place<int64_t>(dims[d]);
            shape_off = b.end_vector(nd);
        }
        int64_t axes_off = 0;
        int32_t na = axes_count[i];
        if (na > 0) {
            // Python creates the axis strings in order, then the vector.
            int64_t axis_offs[16];
            if (na > 16) return -2;
            for (int32_t a = 0; a < na; ++a) {
                int32_t idx = axes_idx_flat[axes_start[i] + a];
                axis_offs[a] = b.create_string(str_ptr(idx), str_len(idx));
            }
            b.start_vector(4, na, 4);
            for (int32_t a = na - 1; a >= 0; --a)
                b.prepend_uoffset(axis_offs[a]);
            axes_off = b.end_vector(na);
        }
        int64_t source_off = 0;
        if (source_idx[i] >= 0)
            source_off =
                b.create_string(str_ptr(source_idx[i]), str_len(source_idx[i]));
        int64_t label_off = 0;
        if (label_idx[i] >= 0)
            label_off =
                b.create_string(str_ptr(label_idx[i]), str_len(label_idx[i]));
        int64_t unit_off =
            b.create_string(str_ptr(unit_idx[i]), str_len(unit_idx[i]));
        int64_t name_off =
            b.create_string(str_ptr(name_idx[i]), str_len(name_idx[i]));

        TableWriter t(&b, 8);
        t.slot_uoffset(0, name_off);
        t.slot_uoffset(1, unit_off);
        if (label_off) t.slot_uoffset(2, label_off);
        if (source_off) t.slot_uoffset(3, source_off);
        t.slot_i8(4, dtype_codes[i], 0);
        if (axes_off) t.slot_uoffset(5, axes_off);
        if (shape_off) t.slot_uoffset(6, shape_off);
        t.slot_uoffset(7, data_off);
        var_offs[static_cast<size_t>(i)] = t.end();
        if (b.overflow) return -1;
    }

    b.start_vector(4, n_vars, 4);
    for (int32_t i = n_vars - 1; i >= 0; --i)
        b.prepend_uoffset(var_offs[static_cast<size_t>(i)]);
    int64_t vars_vec = b.end_vector(n_vars);
    int64_t src_off = b.create_string(str_ptr(source_name_idx),
                                      str_len(source_name_idx));

    TableWriter root(&b, 3);
    root.slot_uoffset(0, src_off);
    root.slot_i64(1, timestamp, 0);
    root.slot_uoffset(2, vars_vec);
    int64_t root_off = root.end();

    // Finish(root, file_identifier=b"da00"): python does
    // Prep(minalign, uoffset+file_id), places the id, then the root.
    b.prep(b.minalign, 4 + 4);
    static const uint8_t fid[4] = {'d', 'a', '0', '0'};
    b.place_bytes(fid, 4);
    b.prepend_uoffset(root_off);
    if (b.overflow) return -1;

    int64_t n = b.cap - b.head;
    std::memmove(out, out + b.head, static_cast<size_t>(n));
    return n;
}

}  // extern "C"

"""Per-service preprocessor factories.

Parity with reference ``preprocessors/detector_data.py:23``,
``data_reduction.py:15``, ``timeseries.py:30``: each service maps stream
kinds to accumulator types; streams returning None are dropped at the
preprocessor (the service consumes but ignores them).
"""

from __future__ import annotations

from ..core.message import StreamId, StreamKind
from .accumulators import Cumulative, LatestValueAccumulator
from .event_data import ToEventBatch
from .to_nxlog import ToNXlog

__all__ = [
    "DetectorPreprocessorFactory",
    "MonitorPreprocessorFactory",
    "ReductionPreprocessorFactory",
    "TimeseriesPreprocessorFactory",
]


class _KindBasedFactory:
    """Shared kind -> accumulator dispatch."""

    event_kinds: frozenset[StreamKind] = frozenset()
    dense_kinds: frozenset[StreamKind] = frozenset()
    log_kinds: frozenset[StreamKind] = frozenset()
    latest_kinds: frozenset[StreamKind] = frozenset(
        {StreamKind.LIVEDATA_ROI, StreamKind.DEVICE}
    )

    def __init__(self, *, min_bucket: int | None = None) -> None:
        self._min_bucket = min_bucket

    def make_preprocessor(self, stream: StreamId):
        kind = stream.kind
        if kind in self.event_kinds:
            return ToEventBatch(min_bucket=self._min_bucket)
        if kind in self.dense_kinds:
            return Cumulative(clear_on_get=True)
        if kind in self.log_kinds:
            return ToNXlog(name=stream.name)
        if kind in self.latest_kinds:
            return LatestValueAccumulator()
        return None


class DetectorPreprocessorFactory(_KindBasedFactory):
    """Detector service: ev44 events, ad00 frames, ROI, logs as context."""

    event_kinds = frozenset({StreamKind.DETECTOR_EVENTS})
    dense_kinds = frozenset({StreamKind.AREA_DETECTOR})
    log_kinds = frozenset({StreamKind.LOG})


class MonitorPreprocessorFactory(_KindBasedFactory):
    """Monitor service: ev44 monitor events + da00 histogram-mode."""

    event_kinds = frozenset({StreamKind.MONITOR_EVENTS})
    dense_kinds = frozenset({StreamKind.MONITOR_COUNTS})
    log_kinds = frozenset({StreamKind.LOG})


class ReductionPreprocessorFactory(_KindBasedFactory):
    """Full reduction: detectors + monitors (both modes) + logs."""

    event_kinds = frozenset(
        {StreamKind.DETECTOR_EVENTS, StreamKind.MONITOR_EVENTS}
    )
    dense_kinds = frozenset({StreamKind.MONITOR_COUNTS, StreamKind.AREA_DETECTOR})
    log_kinds = frozenset({StreamKind.LOG})


class TimeseriesPreprocessorFactory(_KindBasedFactory):
    """Timeseries service: logs are the primary data, not context."""

    log_kinds = frozenset()

    def make_preprocessor(self, stream: StreamId):
        if stream.kind in (StreamKind.LOG, StreamKind.DEVICE):
            # Logs and synthesised device streams are primary here
            # (republished as data — the device case is the NICOS readback
            # history) but additionally exposed as context so jobs may
            # gate/parameterize on them — the wavelength-LUT job consumes
            # chopper setpoint streams this way while the plain timeseries
            # job republishes them. Other services consume both kinds as
            # context only, via the kind-based default.
            acc = ToNXlog(name=stream.name)
            acc.is_context = False  # type: ignore[misc]
            acc.also_context = True  # type: ignore[attr-defined]
            return acc
        return None

"""Dense-data accumulators over labeled DataArrays.

Parity with reference ``preprocessors/accumulators.py``: ``Cumulative``
(+= with restart on structural mismatch, reference :238-261),
``LatestValueAccumulator`` (context, :57), ``NullAccumulator`` (:46).
The reference's NoCopyAccumulator exists to avoid deepcopying a 500 MB
histogram on every read (:96-97). That problem does not arise here *by
construction*: large histograms are device-resident kernel state with
fold semantics (ops/histogram.py — window and cumulative share one
scatter, reads are device views), and host-side accumulators only ever
hold the small dense outputs. ``Cumulative`` therefore defaults to
no-copy reads; ``WindowedCumulative`` provides the paired
window/cumulative semantics for dense streams that never touch the
accelerator, staying aliasing-safe by transferring window ownership on
``take`` and copying the cumulative.
"""

from __future__ import annotations

from typing import ClassVar

import numpy as np

from ..core.timestamp import Timestamp
from ..utils.labeled import DataArray, Variable

__all__ = [
    "Cumulative",
    "LatestValueAccumulator",
    "NullAccumulator",
    "WindowedCumulative",
]


class NullAccumulator:
    """Swallows everything; for streams a service must consume but ignore."""

    is_context: ClassVar[bool] = False

    def add(self, timestamp: Timestamp, data: object) -> None:
        pass

    def get(self) -> None:
        return None

    def clear(self) -> None:
        pass

    def release_buffers(self) -> None:
        pass


class LatestValueAccumulator:
    """Keeps the most recent value — context streams (motor positions,
    chopper settings) that parameterize workflows. is_context=True gates
    job activation until a value exists (ADR 0002)."""

    is_context: ClassVar[bool] = True

    def __init__(self) -> None:
        self._value = None
        self._timestamp: Timestamp | None = None

    def add(self, timestamp: Timestamp, data: object) -> None:
        if self._timestamp is None or timestamp >= self._timestamp:
            self._value = data
            self._timestamp = timestamp

    @property
    def has_value(self) -> bool:
        return self._value is not None

    def get(self):
        if self._value is None:
            raise ValueError("LatestValueAccumulator is empty")
        return self._value

    def clear(self) -> None:
        self._value = None
        self._timestamp = None

    def release_buffers(self) -> None:
        pass


class Cumulative:
    """Running += of DataArrays, restarting when structure changes.

    A structural mismatch (different dims/shape/unit/coords — e.g. the
    upstream reconfigured its binning or an ad00 camera changed ROI) resets
    the accumulation to the new value instead of erroring, matching the
    reference's restart-on-mismatch behavior (accumulators.py:238-261).

    This subsumes the reference's ``reset_coord`` knob
    (NoCopyAccumulator:114-127): geometry is carried as coordinates
    (monitor position, detector transform), and ``same_structure`` compares
    coordinate *values* — so accumulation already restarts when the
    geometry moves, without naming the coord up front.

    ``clear_on_get`` gives window semantics (value since last read);
    otherwise since-start. Reads are no-copy by default: callers must not
    mutate the returned array (copy_on_get=True for defensive copies).
    """

    is_context: ClassVar[bool] = False

    def __init__(
        self, *, clear_on_get: bool = False, copy_on_get: bool = False
    ) -> None:
        self._clear_on_get = clear_on_get
        self._copy_on_get = copy_on_get
        self._value: DataArray | None = None

    def add(self, timestamp: Timestamp, data: DataArray) -> None:
        if self._value is not None and self._value.same_structure(data):
            self._value += data
        else:
            # restart: first value, or structure changed upstream (incl.
            # geometry coords — see class docstring)
            self._value = data.copy()

    @property
    def is_empty(self) -> bool:
        return self._value is None

    @property
    def current(self) -> DataArray | None:
        """No-copy peek at the accumulated value (None when empty);
        callers must not mutate it."""
        return self._value

    def get(self) -> DataArray:
        if self._value is None:
            raise ValueError("Cumulative accumulator is empty")
        value = self._value
        if self._copy_on_get:
            value = value.copy()
        if self._clear_on_get:
            self._value = None
        return value

    def clear(self) -> None:
        self._value = None

    def release_buffers(self) -> None:
        pass


def _zero_like(da: DataArray) -> DataArray:
    out = da.copy()
    out.data = Variable(
        np.zeros_like(np.asarray(da.values)), da.dims, da.unit
    )
    return out


class WindowedCumulative:
    """Paired window/cumulative views of one dense stream.

    One ``add`` feeds both views; ``take`` returns ``(window,
    cumulative)`` and resets the window while the cumulative persists —
    the host-side analog of the device kernel's fold semantics
    (docs/design/fold-semantics.md), for the non-event streams that
    never touch the accelerator: da00 camera frames, rebinned monitor
    histograms, dense log aggregates.

    Composed from two :class:`Cumulative` instances so restart-on-
    mismatch semantics live in exactly one place. Incoming samples are
    unit-aligned to the cumulative before feeding the window: a window
    restarting just after ``take`` must not adopt a new compatible unit
    while the cumulative keeps converting into its original one — both
    views of one stream always share a unit.
    """

    is_context: ClassVar[bool] = False

    def __init__(self) -> None:
        self._cumulative = Cumulative(copy_on_get=True)
        self._window = Cumulative(clear_on_get=True)

    def add(self, timestamp: Timestamp, data: DataArray) -> None:
        self._cumulative.add(timestamp, data)
        anchor = self._cumulative.current
        if anchor is not None and anchor.unit != data.unit:
            data = data.to_unit(anchor.unit)
        self._window.add(timestamp, data)

    @property
    def is_empty(self) -> bool:
        return self._cumulative.is_empty

    def take(self) -> tuple[DataArray, DataArray]:
        """(window, cumulative); the window transfers ownership and
        resets, the cumulative is a defensive copy."""
        cumulative = self._cumulative.get()
        if self._window.is_empty:
            window = _zero_like(cumulative)
        else:
            window = self._window.get()
        return window, cumulative

    def clear(self) -> None:
        self._window.clear()
        self._cumulative.clear()

    def release_buffers(self) -> None:
        pass


"""f144 log chunks -> growing NXlog-style time/value DataArray.

Parity with reference ``preprocessors/to_nxlog.py:15``: accumulates
(time, value) samples into a time-sorted DataArray with a ns-epoch time
coord. Context accumulator (is_context=True): log values parameterize
workflows. Backed by doubling host arrays like the reference's growable
buffers, with no-copy reads of the filled slice.
"""

from __future__ import annotations

from typing import ClassVar

import numpy as np

from ..core.timestamp import Timestamp
from ..utils.labeled import DataArray, Variable
from ..utils.units import Unit, unit as parse_unit

__all__ = ["LogData", "ToNXlog"]


class LogData:
    """One decoded f144 sample (or batch of samples).

    ``target``/``idle`` are populated only on synthesized Device samples
    (DeviceSynthesizer merges a motor's RBV/VAL/DMOV substreams into one
    stream; reference kafka/device_synthesizer.py).
    """

    __slots__ = ("idle", "target", "time", "value")

    def __init__(
        self,
        time: np.ndarray | int,
        value: np.ndarray,
        target: float | None = None,
        idle: bool | None = None,
    ) -> None:
        self.time = np.atleast_1d(np.asarray(time, dtype=np.int64))  # ns epoch
        self.value = np.atleast_1d(np.asarray(value))
        self.target = target
        self.idle = idle

    def samples(self) -> list[tuple[int, float]]:
        """``(time_ns, value)`` pairs for consumers that walk sample-wise.

        An f144 payload can carry an array value under a single timestamp
        (the adapter keeps array values whole); the one timestamp then
        applies to every element. Mismatched multi-element lengths raise.
        """
        if self.time.size == 1 and self.value.size != 1:
            times: np.ndarray = np.broadcast_to(self.time, self.value.shape)
        else:
            times = self.time
        return list(zip(times.tolist(), self.value.tolist(), strict=True))


class ToNXlog:
    """Accumulates log samples into a growing time/value series."""

    is_context: ClassVar[bool] = True

    def __init__(self, value_unit: str | Unit | None = None, name: str = "") -> None:
        self._unit = parse_unit(value_unit)
        self._name = name
        self._capacity = 64
        self._times = np.zeros(self._capacity, dtype=np.int64)
        self._values: np.ndarray | None = None
        self._n = 0
        self._sorted = True

    def _grow(self, needed: int) -> None:
        cap = self._capacity
        while cap < needed:
            cap *= 2
        times = np.zeros(cap, dtype=np.int64)
        times[: self._n] = self._times[: self._n]
        self._times = times
        if self._values is not None:
            values = np.zeros((cap,) + self._values.shape[1:], self._values.dtype)
            values[: self._n] = self._values[: self._n]
            self._values = values
        self._capacity = cap

    def add(self, timestamp: Timestamp, data: LogData) -> None:  # noqa: ARG002
        k = data.time.shape[0]
        if k == 0:
            return
        if self._values is None:
            self._values = np.zeros(
                (self._capacity,) + data.value.shape[1:], data.value.dtype
            )
        if self._n + k > self._capacity:
            self._grow(self._n + k)
        self._times[self._n : self._n + k] = data.time
        self._values[self._n : self._n + k] = data.value
        if self._n > 0 and data.time[0] < self._times[self._n - 1]:
            self._sorted = False
        self._n += k

    @property
    def n_samples(self) -> int:
        return self._n

    @property
    def has_value(self) -> bool:
        return self._n > 0

    def get(self) -> DataArray:
        if self._n == 0:
            raise ValueError("ToNXlog is empty")
        if not self._sorted:
            order = np.argsort(self._times[: self._n], kind="stable")
            self._times[: self._n] = self._times[: self._n][order]
            self._values[: self._n] = self._values[: self._n][order]
            self._sorted = True
        dims = ("time",) + tuple(
            f"dim_{i}" for i in range(1, self._values.ndim)
        )
        return DataArray(
            Variable(self._values[: self._n], dims, self._unit),
            coords={"time": Variable(self._times[: self._n], ("time",), "ns")},
            name=self._name,
        )

    def latest(self):
        if self._n == 0:
            raise ValueError("ToNXlog is empty")
        return self._values[self._n - 1]

    def clear(self) -> None:
        self._n = 0
        self._sorted = True

    def release_buffers(self) -> None:
        pass

"""Per-stream accumulators — the host-side half of the hot path.

Parity with reference ``src/ess/livedata/preprocessors/`` (SURVEY.md
section 2.3), redesigned for the TPU pipeline: where the reference
accumulates ev44 chunks into scipp *binned* arrays (ToNXevent_data) and
pre-groups them by pixel (GroupByPixel), here events are only *staged* into
fixed-shape padded device batches (``ToEventBatch``) — projection, grouping
and binning all happen inside the jitted scatter kernel (ops/histogram.py).
Dense accumulators (Cumulative, LatestValue, ToNXlog) remain host-side over
labeled DataArrays.
"""

from .accumulators import (
    Cumulative,
    LatestValueAccumulator,
    NullAccumulator,
)
from .event_data import DetectorEvents, MonitorEvents, StagedEvents, ToEventBatch
from .to_nxlog import LogData, ToNXlog

__all__ = [
    "Cumulative",
    "DetectorEvents",
    "LatestValueAccumulator",
    "LogData",
    "MonitorEvents",
    "NullAccumulator",
    "StagedEvents",
    "ToEventBatch",
    "ToNXlog",
]

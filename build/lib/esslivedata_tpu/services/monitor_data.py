"""Monitor-data service (reference: services/monitor_data.py:16)."""

from __future__ import annotations

from ..kafka.routes import RoutingAdapterBuilder
from ..preprocessors.factories import MonitorPreprocessorFactory
from .service_factory import DataServiceBuilder, DataServiceRunner

__all__ = ["main", "make_monitor_service_builder"]


def make_monitor_service_builder(
    *,
    instrument: str,
    dev: bool = False,
    batcher=None,
    job_threads: int = 5,
    heartbeat_interval_s: float = 2.0,
    snapshot_dir: str | None = None,
) -> DataServiceBuilder:
    def routes(mapping):
        return (
            RoutingAdapterBuilder(stream_mapping=mapping)
            .with_monitor_route()
            .with_logdata_route()
            .with_run_control_route()
            .with_commands_route()
            .build()
        )

    return DataServiceBuilder(
        instrument=instrument,
        service_name="monitor_data",
        preprocessor_factory=MonitorPreprocessorFactory(),
        route_builder=routes,
        batcher=batcher,
        job_threads=job_threads,
        dev=dev,
        heartbeat_interval_s=heartbeat_interval_s,
        snapshot_dir=snapshot_dir,
    )


def main(argv: list[str] | None = None) -> int:
    return DataServiceRunner(
        service_name="monitor_data", make_builder=make_monitor_service_builder
    ).run(argv)


if __name__ == "__main__":
    raise SystemExit(main())

"""Service entry points and assembly (reference: services/ +
service_factory.py; SURVEY.md section 2.5)."""

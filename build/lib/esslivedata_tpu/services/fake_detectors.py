"""Standalone fake detector producer: synthesizes 14 Hz ev44 streams onto a
real broker (reference: services/fake_detectors.py FakeDetectorSource:52).
Without confluent_kafka it can print-to-stdout for smoke checks."""

from __future__ import annotations

import logging
import time

from ..config.instrument import instrument_registry
from ..core.constants import PULSE_RATE_HZ
from ..core.service import get_env_defaults, setup_arg_parser
from .fake_sources import (
    FakeDetectorStream,
    ReplayDetectorStream,
    load_nexus_events,
)

__all__ = ["main"]

logger = logging.getLogger(__name__)


def main(argv: list[str] | None = None) -> int:
    parser = setup_arg_parser("fake ev44 detector producer")
    parser.add_argument("--events-per-pulse", type=int, default=1000)
    parser.add_argument(
        "--replay",
        default=None,
        metavar="NEXUS_FILE",
        help="replay recorded NXevent_data instead of synthesizing "
        "(reference FakeDetectorSource nexus_file); banks present in the "
        "recording replay with their recorded pixel/TOF distributions "
        "and per-pulse raggedness, others stay synthetic",
    )
    parser.add_argument("--kafka-bootstrap", default=None, help="override the broker from the kafka config namespace")
    parser.add_argument("--pulses", type=int, default=0, help="0 = run forever")
    parser.add_argument("--dry-run", action="store_true")
    parser.set_defaults(**get_env_defaults(parser))
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level)

    instrument = instrument_registry[args.instrument]
    prefix = f"dev_{args.instrument}" if args.dev else args.instrument
    recorded = {}
    if args.replay:
        recorded = load_nexus_events(args.replay)
        logger.info(
            "replaying %s: %s",
            args.replay,
            {k: v.n_events for k, v in recorded.items()},
        )
    streams = []
    for i, (name, det) in enumerate(instrument.detectors.items()):
        if name in recorded:
            streams.append(
                ReplayDetectorStream(
                    topic=f"{prefix}_detector",
                    source_name=det.source_name,
                    recorded=recorded[name],
                    events_per_pulse=args.events_per_pulse,
                )
            )
        else:
            streams.append(
                FakeDetectorStream(
                    topic=f"{prefix}_detector",
                    source_name=det.source_name,
                    detector_ids=(
                        det.detector_number
                        if det.detector_number is not None
                        else det.pixel_ids
                    ),
                    events_per_pulse=args.events_per_pulse,
                    seed=i,
                )
            )

    producer = None
    if not args.dry_run:
        try:
            from confluent_kafka import Producer

            from ..kafka.consumer import kafka_client_config

            producer = Producer(kafka_client_config(bootstrap_override=args.kafka_bootstrap))
        except ImportError:
            logger.error("confluent_kafka not installed; use --dry-run")
            return 2

    period = 1.0 / PULSE_RATE_HZ
    produced = 0
    try:
        while args.pulses == 0 or produced < args.pulses:
            t0 = time.monotonic()
            for stream in streams:
                for msg in stream.pulses(1):
                    if producer is None:
                        logger.info(
                            "pulse %d: %d bytes -> %s",
                            produced,
                            len(msg.value()),
                            msg.topic(),
                        )
                    else:
                        producer.produce(msg.topic(), msg.value())
            if producer is not None:
                producer.poll(0)
            produced += 1
            time.sleep(max(0.0, period - (time.monotonic() - t0)))
    except KeyboardInterrupt:
        pass
    if producer is not None:
        producer.flush(5)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Data-reduction service (reference: services/data_reduction.py:18):
hosts full reductions consuming detectors + monitors + logs."""

from __future__ import annotations

from ..config.instrument import instrument_registry
from ..kafka.routes import RoutingAdapterBuilder
from ..preprocessors.factories import ReductionPreprocessorFactory
from .service_factory import DataServiceBuilder, DataServiceRunner

__all__ = ["main", "make_reduction_service_builder"]


def make_reduction_service_builder(
    *,
    instrument: str,
    dev: bool = False,
    batcher=None,
    job_threads: int = 5,
    heartbeat_interval_s: float = 2.0,
    snapshot_dir: str | None = None,
) -> DataServiceBuilder:
    # Merged-detector instruments (BIFROST) address reductions at the
    # single logical stream; the reduction service must apply the same
    # adaptation the detector service does or jobs subscribed to the
    # merged name never see events.
    merge = instrument_registry[instrument].merge_detectors

    def routes(mapping):
        return (
            RoutingAdapterBuilder(stream_mapping=mapping)
            .with_detector_route(merge_detectors=merge)
            .with_monitor_route()
            .with_logdata_route()
            .with_run_control_route()
            .with_commands_route()
            .build()
        )

    return DataServiceBuilder(
        instrument=instrument,
        service_name="data_reduction",
        preprocessor_factory=ReductionPreprocessorFactory(),
        route_builder=routes,
        batcher=batcher,
        job_threads=job_threads,
        dev=dev,
        heartbeat_interval_s=heartbeat_interval_s,
        snapshot_dir=snapshot_dir,
    )


def main(argv: list[str] | None = None) -> int:
    return DataServiceRunner(
        service_name="data_reduction", make_builder=make_reduction_service_builder
    ).run(argv)


if __name__ == "__main__":
    raise SystemExit(main())

"""Timeseries (logdata) service (reference: services/timeseries.py:20).
Uses the naive batcher: log samples should flow immediately. Wraps the
adapted source with DeviceSynthesizer + ChopperSynthesizer (reference
services/timeseries.py:44-51)."""

from __future__ import annotations

from ..core.message_batcher import NaiveMessageBatcher
from ..kafka.chopper_synthesizer import ChopperSynthesizer
from ..kafka.device_synthesizer import DeviceSynthesizer
from ..kafka.routes import RoutingAdapterBuilder
from ..preprocessors.factories import TimeseriesPreprocessorFactory
from .service_factory import DataServiceBuilder, DataServiceRunner

__all__ = ["main", "make_timeseries_service_builder"]


def _synthesizing_source(source, instrument):
    """Chain device merge + chopper-cascade synthesis over the adapted source.

    DeviceSynthesizer is skipped when the instrument declares no devices;
    ChopperSynthesizer always wraps — its chopperless bootstrap tick is the
    wavelength-LUT workflow's recompute trigger on instruments without
    choppers (reference chopper_synthesizer.py:199-202)."""
    if devices := instrument.devices:
        source = DeviceSynthesizer(source, devices=devices)
    return ChopperSynthesizer(
        source,
        chopper_names=instrument.choppers,
        delay_atol=instrument.chopper_delay_atol_ns,
    )


def make_timeseries_service_builder(
    *,
    instrument: str,
    dev: bool = False,
    batcher=None,
    job_threads: int = 5,
    heartbeat_interval_s: float = 2.0,
    snapshot_dir: str | None = None,
) -> DataServiceBuilder:
    def routes(mapping):
        return (
            RoutingAdapterBuilder(stream_mapping=mapping)
            .with_logdata_route()
            .with_run_control_route()
            .with_commands_route()
            .build()
        )

    return DataServiceBuilder(
        instrument=instrument,
        service_name="timeseries",
        preprocessor_factory=TimeseriesPreprocessorFactory(),
        route_builder=routes,
        batcher=batcher or NaiveMessageBatcher(),
        job_threads=job_threads,
        dev=dev,
        heartbeat_interval_s=heartbeat_interval_s,
        source_decorator=_synthesizing_source,
        snapshot_dir=snapshot_dir,
    )


def main(argv: list[str] | None = None) -> int:
    return DataServiceRunner(
        service_name="timeseries", make_builder=make_timeseries_service_builder
    ).run(argv)


if __name__ == "__main__":
    raise SystemExit(main())

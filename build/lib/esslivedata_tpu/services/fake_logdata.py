"""Standalone fake f144 log producer (reference: services/fake_logdata.py)."""

from __future__ import annotations

import logging
import time

from ..config.instrument import instrument_registry
from ..core.constants import PULSE_RATE_HZ
from ..core.service import get_env_defaults, setup_arg_parser
from .fake_sources import FakeLogStream

__all__ = ["main"]

logger = logging.getLogger(__name__)


def main(argv: list[str] | None = None) -> int:
    parser = setup_arg_parser("fake f144 log producer")
    parser.add_argument("--kafka-bootstrap", default=None, help="override the broker from the kafka config namespace")
    parser.add_argument("--pulses", type=int, default=0)
    parser.add_argument("--dry-run", action="store_true")
    parser.set_defaults(**get_env_defaults(parser))
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level)

    instrument = instrument_registry[args.instrument]
    prefix = f"dev_{args.instrument}" if args.dev else args.instrument
    streams = [
        FakeLogStream(topic=f"{prefix}_motion", source_name=source)
        for source in instrument.log_sources.values()
    ]
    producer = None
    if not args.dry_run:
        try:
            from confluent_kafka import Producer

            from ..kafka.consumer import kafka_client_config

            producer = Producer(kafka_client_config(bootstrap_override=args.kafka_bootstrap))
        except ImportError:
            logger.error("confluent_kafka not installed; use --dry-run")
            return 2
    period = 1.0 / PULSE_RATE_HZ
    produced = 0
    try:
        while args.pulses == 0 or produced < args.pulses:
            t0 = time.monotonic()
            for stream in streams:
                for msg in stream.pulses(1):
                    if producer is None:
                        logger.info("pulse %d -> %s", produced, msg.topic())
                    else:
                        producer.produce(msg.topic(), msg.value())
            if producer is not None:
                producer.poll(0)
            produced += 1
            time.sleep(max(0.0, period - (time.monotonic() - t0)))
    except KeyboardInterrupt:
        pass
    if producer is not None:
        producer.flush(5)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""DataArray <-> da00 variable-list conversion.

Parity with reference ``kafka/scipp_da00_compat.py``: the data variable is
named ``signal``; coords ride as additional variables named by coord name
(edge coords are naturally length N+1 along their axis); masks as boolean
variables prefixed ``mask:``. Units travel as strings through the unit
parser, falling back to dimensionless on unknown strings (the wire must
never kill the service; reference behavior is per-message error
containment, message_adapter.py:592-624).
"""

from __future__ import annotations

import logging

import numpy as np

from ..utils.labeled import DataArray, Variable
from ..utils.units import UnitError, unit as parse_unit
from .wire import Da00Variable

logger = logging.getLogger(__name__)

__all__ = ["dataarray_to_da00", "da00_to_dataarray"]

_SIGNAL = "signal"
_MASK_PREFIX = "mask:"


def _safe_unit(s: str):
    try:
        return parse_unit(s)
    except UnitError:
        logger.warning("Unknown unit %r on wire; treating as dimensionless", s)
        return parse_unit(None)


def dataarray_to_da00(da: DataArray) -> list[Da00Variable]:
    out = [
        Da00Variable(
            name=_SIGNAL,
            unit=repr(da.unit),
            axes=da.dims,
            data=np.asarray(da.values),
        )
    ]
    for name, coord in da.coords.items():
        out.append(
            Da00Variable(
                name=name,
                unit=repr(coord.unit),
                axes=coord.dims,
                data=coord.numpy,
            )
        )
    for name, mask in da.masks.items():
        out.append(
            Da00Variable(
                name=_MASK_PREFIX + name,
                unit="",
                axes=mask.dims,
                data=mask.numpy.astype(np.uint8),
            )
        )
    return out


def da00_to_dataarray(variables: list[Da00Variable], name: str = "") -> DataArray:
    signal = next((v for v in variables if v.name == _SIGNAL), None)
    if signal is None:
        raise ValueError("da00 payload has no 'signal' variable")
    data = Variable(signal.data, signal.axes, _safe_unit(signal.unit))
    coords = {}
    masks = {}
    for v in variables:
        if v.name == _SIGNAL:
            continue
        if v.name.startswith(_MASK_PREFIX):
            masks[v.name[len(_MASK_PREFIX) :]] = Variable(
                v.data.astype(bool), v.axes, None
            )
        else:
            coords[v.name] = Variable(v.data, v.axes, _safe_unit(v.unit))
    return DataArray(data, coords=coords, masks=masks, name=name)

"""Transport layer: wire codecs, consumers, adapters, sinks.

Parity with reference ``src/ess/livedata/kafka/`` (SURVEY.md section 2.2).
The reference decodes FlatBuffers through the ess-streaming-data-types
package and talks to brokers through librdkafka/confluent_kafka; here the
codecs are implemented clean-room on the flatbuffers runtime (zero-copy
numpy views into message buffers on decode — the ev44 fast path feeds the
device staging buffer directly), and the consumer protocol is narrow so
tests and fakes plug in without a broker (the reference's central test
pattern, SURVEY.md section 4.2). confluent_kafka is optional: the real
consumer/producer live behind the same protocols.
"""

from .wire import (
    Ad00Image,
    Da00Variable,
    Ev44Message,
    F144Message,
    RunStartMessage,
    RunStopMessage,
    X5f2Status,
    decode_ad00,
    decode_da00,
    decode_ev44,
    decode_f144,
    decode_pl72,
    decode_6s4t,
    decode_x5f2,
    encode_ad00,
    encode_da00,
    encode_ev44,
    encode_f144,
    encode_pl72,
    encode_6s4t,
    encode_x5f2,
    get_schema,
)

__all__ = [
    "Ad00Image",
    "Da00Variable",
    "Ev44Message",
    "F144Message",
    "RunStartMessage",
    "RunStopMessage",
    "X5f2Status",
    "decode_6s4t",
    "decode_ad00",
    "decode_da00",
    "decode_ev44",
    "decode_f144",
    "decode_pl72",
    "decode_x5f2",
    "encode_6s4t",
    "encode_ad00",
    "encode_da00",
    "encode_ev44",
    "encode_f144",
    "encode_pl72",
    "encode_x5f2",
    "get_schema",
]

"""NICOS-compatible x5f2 status envelopes.

NICOS (the ESS instrument-control system) monitors livedata services and
jobs through x5f2 status messages whose ``status_json`` carries a NICOS
daemon status *code* plus a typed payload, and whose ``service_id``
encodes what is being monitored (contract studied from the reference
repo scipp/esslivedata, src/ess/livedata/kafka/x5f2_compat.py:93-487;
SURVEY §2.10 names NICOS interop a wire-compatibility requirement). This module owns that mapping for both
directions:

- **Codes**: the NICOS daemon status constants (OK=200 ... UNKNOWN=999),
  derived from our service/job states so a NICOS panel colors a livedata
  job exactly like any beamline device.
- **Identities**: ``instrument:service_name:worker`` for services,
  ``source_name:job_number`` for jobs — stable addressing a NICOS cache
  can key on.
- **Envelopes**: ``status_json = {"status": <code>, "message": {...}}``
  with a ``message_type`` discriminator (``service`` | ``job``) so one
  topic carries both kinds; payloads are our own status models.

Decoding accepts the enveloped form and the legacy bare-``ServiceStatus``
JSON, so an upgraded dashboard keeps working against not-yet-upgraded
services. (The reverse — an old dashboard against new services — needs
the dashboard upgraded first; the envelope is what NICOS consumes, so
producers cannot stay on the bare form.)
"""

from __future__ import annotations

import json
import uuid
from enum import IntEnum

from pydantic import BaseModel, Field

from ..core.job import JobState, JobStatus, ServiceStatus
from . import wire

__all__ = [
    "JobIdentity",
    "NicosStatus",
    "ServiceIdentity",
    "decode_status",
    "job_state_code",
    "job_status_to_x5f2",
    "service_code",
    "service_state_code",
    "service_status_envelope",
    "service_status_to_x5f2",
    "worst_status",
]


class NicosStatus(IntEnum):
    """NICOS daemon status constants (the public NICOS device states)."""

    OK = 200
    WARNING = 210
    BUSY = 220
    NOTREACHED = 230
    DISABLED = 235
    ERROR = 240
    UNKNOWN = 999


_JOB_STATE_CODES: dict[JobState, NicosStatus] = {
    # A scheduled job is "moving into position": BUSY, not an error.
    JobState.SCHEDULED: NicosStatus.BUSY,
    # Gated on context: operable but degraded until the context arrives.
    JobState.PENDING_CONTEXT: NicosStatus.WARNING,
    JobState.ACTIVE: NicosStatus.OK,
    JobState.FINISHING: NicosStatus.OK,
    JobState.WARNING: NicosStatus.WARNING,
    JobState.ERROR: NicosStatus.ERROR,
    JobState.STOPPED: NicosStatus.DISABLED,
}


def job_state_code(state: JobState) -> NicosStatus:
    return _JOB_STATE_CODES.get(state, NicosStatus.UNKNOWN)


_SERVICE_STATE_CODES: dict[str, NicosStatus] = {
    "starting": NicosStatus.BUSY,
    "running": NicosStatus.OK,
    "stopping": NicosStatus.DISABLED,
    "stopped": NicosStatus.DISABLED,
    "error": NicosStatus.ERROR,
}


def service_state_code(state: str) -> NicosStatus:
    return _SERVICE_STATE_CODES.get(state, NicosStatus.UNKNOWN)


#: Severity order for aggregation (a service heartbeat reports the worst
#: of its own state and its jobs' states — one glance tells NICOS whether
#: anything under this service needs attention).
_SEVERITY = [
    NicosStatus.OK,
    NicosStatus.BUSY,
    NicosStatus.NOTREACHED,
    NicosStatus.DISABLED,
    NicosStatus.WARNING,
    NicosStatus.ERROR,
    NicosStatus.UNKNOWN,
]


def worst_status(codes) -> NicosStatus:
    codes = list(codes)
    if not codes:
        return NicosStatus.OK
    return max(codes, key=_SEVERITY.index)


# -- identities --------------------------------------------------------------


class ServiceIdentity(BaseModel, frozen=True):
    """``instrument:service_name:worker`` — one running service process."""

    instrument: str
    service_name: str
    worker: str = ""

    @classmethod
    def parse(cls, raw: str) -> "ServiceIdentity":
        parts = raw.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"service identity {raw!r} is not instrument:service[:worker]"
            )
        return cls(
            instrument=parts[0],
            service_name=parts[1],
            worker=":".join(parts[2:]),
        )

    def render(self) -> str:
        return f"{self.instrument}:{self.service_name}:{self.worker}"


class JobIdentity(BaseModel, frozen=True):
    """``source_name:job_number`` — one job, across restarts of anything."""

    source_name: str
    job_number: uuid.UUID

    @classmethod
    def parse(cls, raw: str) -> "JobIdentity":
        source, sep, number = raw.rpartition(":")
        if not sep:
            raise ValueError(f"job identity {raw!r} is not source:job_number")
        return cls(source_name=source, job_number=uuid.UUID(number))

    def render(self) -> str:
        return f"{self.source_name}:{self.job_number}"


# -- envelopes ---------------------------------------------------------------


class ServicePayload(BaseModel):
    message_type: str = "service"
    status: ServiceStatus


class JobPayload(BaseModel):
    message_type: str = "job"
    status: JobStatus


class StatusEnvelope(BaseModel):
    """The ``status_json`` document: NICOS code + typed payload."""

    status: NicosStatus
    message: dict = Field(default_factory=dict)


def service_code(status: ServiceStatus) -> NicosStatus:
    """Aggregate code of a service document: worst of its own state and
    its jobs' states (shared by encoding and legacy decoding)."""
    return worst_status(
        [service_state_code(status.state)]
        + [job_state_code(j.state) for j in status.jobs]
    )


def service_status_envelope(status: ServiceStatus) -> str:
    code = service_code(status)
    return StatusEnvelope(
        status=code,
        message=ServicePayload(status=status).model_dump(mode="json"),
    ).model_dump_json()


def _job_envelope(status: JobStatus) -> str:
    return StatusEnvelope(
        status=job_state_code(status.state),
        message=JobPayload(status=status).model_dump(mode="json"),
    ).model_dump_json()


def service_status_to_x5f2(
    status: ServiceStatus,
    *,
    worker: str = "",
    software_version: str = "0.1.0",
    host_name: str = "",
    process_id: int = 0,
    update_interval_ms: int = 2000,
) -> bytes:
    """Full wire form of a service heartbeat a NICOS consumer accepts."""
    return wire.encode_x5f2(
        wire.X5f2Status(
            software_name="esslivedata-tpu",
            software_version=software_version,
            service_id=ServiceIdentity(
                instrument=status.instrument,
                service_name=status.service_name,
                worker=worker,
            ).render(),
            host_name=host_name,
            process_id=process_id,
            update_interval_ms=update_interval_ms,
            status_json=service_status_envelope(status),
        )
    )


def job_status_to_x5f2(
    status: JobStatus,
    *,
    software_version: str = "0.1.0",
    host_name: str = "",
    process_id: int = 0,
    update_interval_ms: int = 2000,
) -> bytes:
    """Per-job heartbeat: service_id addresses the job itself."""
    return wire.encode_x5f2(
        wire.X5f2Status(
            software_name="esslivedata-tpu",
            software_version=software_version,
            service_id=JobIdentity(
                source_name=status.source_name,
                job_number=status.job_number,
            ).render(),
            host_name=host_name,
            process_id=process_id,
            update_interval_ms=update_interval_ms,
            status_json=_job_envelope(status),
        )
    )


def decode_status(payload: bytes):
    """Decode an x5f2 status message.

    Returns ``(code, ServiceStatus | JobStatus, service_id)``. Accepts the
    enveloped form (``message_type`` discriminated) and the legacy bare
    ``ServiceStatus`` JSON (code derived from its state).
    """
    status = wire.decode_x5f2(payload)
    doc = json.loads(status.status_json)
    if "message" in doc and isinstance(doc.get("message"), dict):
        message = doc["message"]
        kind = message.get("message_type")
        code = NicosStatus(doc.get("status", NicosStatus.UNKNOWN))
        if kind == "service":
            return code, ServiceStatus.model_validate(message["status"]), status.service_id
        if kind == "job":
            return code, JobStatus.model_validate(message["status"]), status.service_id
        raise ValueError(f"Unknown status message_type {kind!r}")
    # Legacy bare ServiceStatus heartbeat.
    parsed = ServiceStatus.model_validate(doc)
    return service_code(parsed), parsed, status.service_id

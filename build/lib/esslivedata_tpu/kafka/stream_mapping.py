"""(topic, source_name) -> canonical stream name lookup tables.

Parity with reference ``kafka/stream_mapping.py`` (InputStreamKey:11,
StreamMapping:39, LivedataTopics:22): raw ECDC topics carry many named
sources; services address streams by canonical names declared in the
instrument config. The LUTs here are that translation, per stream kind.
"""

from __future__ import annotations

from collections.abc import Mapping
from dataclasses import dataclass, field

__all__ = [
    "InputStreamKey",
    "LivedataTopics",
    "MERGED_DETECTOR_STREAM",
    "StreamMapping",
]

#: Logical stream name all banks adapt onto when an instrument sets
#: merge_detectors (BIFROST pattern; message_adapter merges at the route).
MERGED_DETECTOR_STREAM = "detector"


@dataclass(frozen=True, slots=True)
class InputStreamKey:
    topic: str
    source_name: str


@dataclass(frozen=True, slots=True)
class LivedataTopics:
    """Our own output/control topics for one instrument."""

    data: str
    status: str
    commands: str
    responses: str
    roi: str
    nicos: str

    @classmethod
    def for_instrument(cls, instrument: str, dev: bool = False) -> "LivedataTopics":
        prefix = f"dev_{instrument}" if dev else instrument
        return cls(
            data=f"{prefix}_livedata_data",
            status=f"{prefix}_livedata_status",
            commands=f"{prefix}_livedata_commands",
            responses=f"{prefix}_livedata_responses",
            roi=f"{prefix}_livedata_roi",
            nicos=f"{prefix}_livedata_nicos",
        )


@dataclass(frozen=True)
class StreamMapping:
    """All input routing knowledge for one instrument's services."""

    instrument: str
    detectors: Mapping[InputStreamKey, str] = field(default_factory=dict)
    monitors: Mapping[InputStreamKey, str] = field(default_factory=dict)
    area_detectors: Mapping[InputStreamKey, str] = field(default_factory=dict)
    logs: Mapping[InputStreamKey, str] = field(default_factory=dict)
    #: Canonical monitor stream names whose ev44 pixel ids are meaningful:
    #: the monitor adapter preserves them (DetectorEvents payload) instead
    #: of taking the pixel-skipping fast path.
    pixellated_monitors: frozenset[str] = frozenset()
    run_control_topics: tuple[str, ...] = ()
    dev: bool = False
    livedata: LivedataTopics | None = None

    def __post_init__(self) -> None:
        if self.livedata is None:
            object.__setattr__(
                self,
                "livedata",
                LivedataTopics.for_instrument(self.instrument, self.dev),
            )

    @property
    def detector_topics(self) -> set[str]:
        return {k.topic for k in self.detectors}

    @property
    def monitor_topics(self) -> set[str]:
        return {k.topic for k in self.monitors}

    @property
    def area_detector_topics(self) -> set[str]:
        return {k.topic for k in self.area_detectors}

    @property
    def log_topics(self) -> set[str]:
        return {k.topic for k in self.logs}

    @property
    def all_input_topics(self) -> set[str]:
        return (
            self.detector_topics
            | self.monitor_topics
            | self.area_detector_topics
            | self.log_topics
            | set(self.run_control_topics)
            | {self.livedata.commands, self.livedata.roi}
        )

    @property
    def all_stream_names(self) -> set[str]:
        """Every canonical stream name any LUT maps onto."""
        return (
            set(self.detectors.values())
            | set(self.monitors.values())
            | set(self.area_detectors.values())
            | set(self.logs.values())
        )

    def filtered(self, names: set[str]) -> "StreamMapping":
        """Restrict every LUT to entries whose canonical name is needed
        (reference StreamMapping.filtered: the service subscribes only to
        streams its hosted specs consume)."""
        return StreamMapping(
            instrument=self.instrument,
            detectors={k: v for k, v in self.detectors.items() if v in names},
            monitors={k: v for k, v in self.monitors.items() if v in names},
            area_detectors={
                k: v for k, v in self.area_detectors.items() if v in names
            },
            logs={k: v for k, v in self.logs.items() if v in names},
            pixellated_monitors=self.pixellated_monitors & names,
            run_control_topics=self.run_control_topics,
            dev=self.dev,
            livedata=self.livedata,
        )


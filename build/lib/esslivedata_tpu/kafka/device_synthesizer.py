"""Synthesize merged per-device streams from f144 motor substreams.

A NICOS-style device (ADR 0001; reference ``kafka/device_synthesizer.py``)
is spread over up to three raw f144 substreams: readback (RBV), setpoint
(VAL), and moving/idle flag (DMOV). Workflows and the dashboard want one
coherent stream per device instead. This module provides that as a
``MessageSource`` decorator sitting after adaptation: raw substream
messages claimed by a device are absorbed, and once the device has been
observed on every substream it is configured with, each further raw sample
produces one merged ``LogData`` sample on a synthetic
``StreamKind.DEVICE`` stream.

Merge semantics (the wire contract, shared with the reference):

- emission is *union-anchored*: any claimed substream event triggers an
  output sample, carrying the latest known value of every other role;
- the merged sample is stamped ``max`` over the constituent sample times,
  so it never predates data it includes;
- batched f144 payloads (multiple samples in one ``LogData``) emit one
  merged sample per raw sample — intermediate motor positions survive.
"""

from __future__ import annotations

import logging
from collections.abc import Iterator, Mapping, Sequence

from ..config.stream import Device
from ..core.message import Message, MessageSource, StreamId, StreamKind
from ..core.timestamp import Timestamp
from ..preprocessors.to_nxlog import LogData

__all__ = ["DeviceSynthesizer"]

logger = logging.getLogger(__name__)


class _Merger:
    """Latest-known sample per role for one device, and the merge itself."""

    __slots__ = ("_latest", "_required", "stream")

    def __init__(self, device_name: str, required_roles: frozenset[str]) -> None:
        self.stream = StreamId(kind=StreamKind.DEVICE, name=device_name)
        self._required = required_roles
        self._latest: dict[str, tuple[Timestamp, float]] = {}

    def ingest(self, role: str, log: LogData) -> Iterator[Message[LogData]]:
        """Fold raw samples in; yield merged samples once bootstrapped."""
        for raw_ns, raw_value in log.samples():
            self._latest[role] = (Timestamp.from_ns(int(raw_ns)), float(raw_value))
            if self._required <= self._latest.keys():
                yield self._merged()

    def _merged(self) -> Message[LogData]:
        stamp = max(t for t, _ in self._latest.values())
        target = self._latest.get("target")
        idle = self._latest.get("idle")
        merged = LogData(
            time=stamp.ns,
            value=self._latest["value"][1],
            target=None if target is None else target[1],
            idle=None if idle is None else bool(idle[1]),
        )
        return Message(timestamp=stamp, stream=self.stream, value=merged)


class DeviceSynthesizer:
    """MessageSource decorator replacing raw substreams with device streams.

    ``devices`` maps device name to its substream configuration; the
    ``value`` substream is mandatory, ``target`` and ``idle`` optional.
    A raw substream may be claimed by at most one device — a conflicting
    configuration is rejected at construction, since silently routing one
    substream into two devices would corrupt both.
    """

    def __init__(
        self,
        wrapped: MessageSource[Message],
        *,
        devices: Mapping[str, Device],
    ) -> None:
        self._wrapped = wrapped
        # Routing: raw substream name -> (role, merger for the owning device).
        self._claims: dict[str, tuple[str, _Merger]] = {}
        for device_name, spec in devices.items():
            roles = {"value": spec.value}
            if spec.target is not None:
                roles["target"] = spec.target
            if spec.idle is not None:
                roles["idle"] = spec.idle
            merger = _Merger(device_name, frozenset(roles))
            for role, substream in roles.items():
                if substream in self._claims:
                    rival = self._claims[substream][1].stream.name
                    raise ValueError(
                        f"devices {rival!r} and {device_name!r} both claim "
                        f"substream {substream!r}; a raw substream may feed "
                        "exactly one device"
                    )
                self._claims[substream] = (role, merger)

    def get_messages(self) -> Sequence[Message]:
        out: list[Message] = []
        for msg in self._wrapped.get_messages():
            claim = self._claims.get(msg.stream.name)
            if claim is None:
                out.append(msg)
                continue
            role, merger = claim
            if isinstance(msg.value, LogData):
                out.extend(merger.ingest(role, msg.value))
            else:
                logger.warning(
                    "device substream %s (%s/%s) carried unexpected payload %s",
                    msg.stream.name,
                    merger.stream.name,
                    role,
                    type(msg.value).__name__,
                )
        return out

"""Fatal-vs-retriable classification of transport errors.

Parity with reference ``kafka/errors.py``: librdkafka-flagged fatal errors
plus auth/misconfiguration codes crash the service (surfacing the problem to
the operator) instead of silently retrying forever. Duck-typed so it works
against real ``confluent_kafka.KafkaError`` objects and the fake error
objects used in broker-free tests.
"""

from __future__ import annotations

__all__ = ["FATAL_ERROR_NAMES", "is_fatal"]

# librdkafka error-name strings treated as fatal in addition to errors the
# library itself flags fatal. Auth failures retried in a loop just spam the
# broker; crashing lets the supervisor (and the operator) see them.
FATAL_ERROR_NAMES = frozenset(
    {
        "TOPIC_AUTHORIZATION_FAILED",
        "GROUP_AUTHORIZATION_FAILED",
        "CLUSTER_AUTHORIZATION_FAILED",
        "SASL_AUTHENTICATION_FAILED",
        "TRANSACTIONAL_ID_AUTHORIZATION_FAILED",
    }
)


def is_fatal(err: object) -> bool:
    """True if the error should crash the service rather than be retried.

    Accepts any object with librdkafka's ``KafkaError`` shape (``fatal()``,
    ``name()``); objects without that shape are treated as retriable.
    """
    fatal = getattr(err, "fatal", None)
    if callable(fatal) and fatal():
        return True
    name = getattr(err, "name", None)
    if callable(name):
        return name() in FATAL_ERROR_NAMES
    return False

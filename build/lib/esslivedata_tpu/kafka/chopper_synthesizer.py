"""Derive chopper-cascade readiness from raw chopper PV traffic.

Wavelength-LUT jobs need a primary trigger saying "every chopper in the
cascade has reached its setpoints" — a signal no upstream producer emits
(reference ``kafka/chopper_synthesizer.py``). This module derives it
in-process, as a ``MessageSource`` decorator that forwards all wrapped
traffic verbatim and injects two kinds of synthetic f144 streams:

- ``<chopper>/delay_setpoint``: the noisy ``<chopper>/delay`` readback is
  plateau-detected; each newly locked level is published once, stamped
  with the time of the raw sample that completed the plateau (not the
  batch end — a batch can contain a lock followed by the start of the
  next ramp, and the setpoint must not carry the ramp's time).
- ``chopper_cascade``: one tick whenever an input changed while every
  configured chopper holds both a cached ``rotation_speed_setpoint`` and
  a locked delay. While locked and idle, the tick is re-emitted every
  ``refresh_every``-th cycle so jobs started after the original lock
  still receive their primary trigger (there is no replay; the LUT
  workflow dedupes on setpoint signature, so refreshes are no-ops for
  already-primed jobs).

Every synthetic message rides the *data clock*: timestamps come from
observed input samples, never from the wall clock. Batchers window on
message timestamps, so a wall-clock-stamped tick could land far outside
any live window during replay and orphan the LUT trigger. Consequently a
chopperless instrument's single vacuous bootstrap tick is deferred until
the first forwarded message supplies a data time (before that, no batch
can close, so nothing is lost by waiting).
"""

from __future__ import annotations

import logging
from collections import deque
from collections.abc import Sequence

import numpy as np

from ..config.chopper import (
    CHOPPER_CASCADE_SOURCE,
    delay_readback_stream,
    delay_setpoint_stream,
    speed_setpoint_stream,
)
from ..core.message import Message, MessageSource, StreamId, StreamKind
from ..core.timestamp import Timestamp
from ..preprocessors.to_nxlog import LogData

__all__ = ["CHOPPER_CASCADE_SOURCE", "CHOPPER_CASCADE_STREAM", "ChopperSynthesizer"]

logger = logging.getLogger(__name__)

CHOPPER_CASCADE_STREAM = StreamId(kind=StreamKind.LOG, name=CHOPPER_CASCADE_SOURCE)


class _ChopperTracker:
    """Lock state for one chopper: cached speed setpoint + delay plateau.

    The delay readback is noisy, so its setpoint is inferred: keep the
    last ``window_size`` samples, and when their spread (std dev) falls
    under ``atol`` the window mean becomes the locked level. The same
    ``atol`` decides whether a later plateau differs enough from the
    current lock to count as a *new* setpoint — one knob for both noise
    rejection and change detection.
    """

    __slots__ = ("_atol", "_delay_lock", "_recent", "_speed", "name")

    def __init__(self, name: str, *, window_size: int, atol: float) -> None:
        self.name = name
        self._atol = atol
        self._recent: deque[float] = deque(maxlen=window_size)
        self._delay_lock: float | None = None
        self._speed: float | None = None

    @property
    def ready(self) -> bool:
        """Both quantities known — this chopper no longer blocks the tick."""
        return self._speed is not None and self._delay_lock is not None

    def feed_delay(self, log: LogData) -> list[tuple[int, float]]:
        """Feed raw readback samples; return ``(time_ns, level)`` per new
        lock, timestamped at the sample that completed the plateau."""
        locks: list[tuple[int, float]] = []
        for raw_ns, raw in log.samples():
            self._recent.append(float(raw))
            if len(self._recent) < self._recent.maxlen:
                continue
            plateau = np.fromiter(self._recent, dtype=float)
            if plateau.std() >= self._atol:
                continue
            level = float(plateau.mean())
            if self._delay_lock is None or abs(level - self._delay_lock) > self._atol:
                self._delay_lock = level
                locks.append((int(raw_ns), level))
        return locks

    def feed_speed(self, log: LogData) -> bool:
        """Cache the clean speed setpoint; True if it actually changed."""
        latest = float(log.value[-1])
        if latest == self._speed:
            return False
        self._speed = latest
        return True


class ChopperSynthesizer:
    """MessageSource decorator injecting cascade-readiness streams."""

    def __init__(
        self,
        wrapped: MessageSource[Message],
        *,
        chopper_names: Sequence[str] = (),
        delay_window_size: int = 5,
        delay_atol: float = 1000.0,
        refresh_every: int = 256,
    ) -> None:
        self._wrapped = wrapped
        self._refresh_every = max(1, refresh_every)
        self._cycle = 0
        self._trackers = [
            _ChopperTracker(name, window_size=delay_window_size, atol=delay_atol)
            for name in chopper_names
        ]
        # Stream-name routing: which tracker and quantity a message feeds.
        self._delay_of = {
            delay_readback_stream(t.name): t for t in self._trackers
        }
        self._speed_of = {
            speed_setpoint_stream(t.name): t for t in self._trackers
        }
        self._ticked_once = False
        self._logged_lock = False
        self._data_clock: Timestamp | None = None

    # -- cycle ------------------------------------------------------------
    def get_messages(self) -> Sequence[Message]:
        self._cycle += 1
        injected: list[Message] = []
        passthrough: list[Message] = []
        changed_at: Timestamp | None = None

        for msg in self._wrapped.get_messages():
            passthrough.append(msg)
            # Only data streams advance the data clock: commands are
            # wall-clock stamped, and a bootstrap tick at "now" would
            # poison the batcher's data-time window for replayed or
            # backlogged data arriving with older timestamps.
            if msg.stream.kind.is_data and (
                self._data_clock is None or msg.timestamp > self._data_clock
            ):
                self._data_clock = msg.timestamp
            if self._observe(msg, injected):
                if changed_at is None or msg.timestamp > changed_at:
                    changed_at = msg.timestamp

        tick_at = self._tick_due(changed_at)
        if tick_at is not None:
            self._ticked_once = True
            injected.append(
                Message(
                    timestamp=tick_at,
                    stream=CHOPPER_CASCADE_STREAM,
                    value=LogData(time=tick_at.ns, value=1),
                )
            )
        return [*injected, *passthrough]

    def _observe(self, msg: Message, injected: list[Message]) -> bool:
        """Feed one message into its tracker; True if an input changed."""
        tracker = self._delay_of.get(msg.stream.name)
        if tracker is not None:
            locks = tracker.feed_delay(msg.value)
            for lock_ns, level in locks:
                injected.append(
                    Message(
                        timestamp=Timestamp.from_ns(lock_ns),
                        stream=StreamId(
                            kind=StreamKind.LOG,
                            name=delay_setpoint_stream(tracker.name),
                        ),
                        value=LogData(time=lock_ns, value=level),
                    )
                )
                logger.info(
                    "chopper %s delay locked at %s", tracker.name, level
                )
            return bool(locks)
        tracker = self._speed_of.get(msg.stream.name)
        if tracker is not None:
            return tracker.feed_speed(msg.value)
        return False

    def _tick_due(self, changed_at: Timestamp | None) -> Timestamp | None:
        """When (in data time) to emit a cascade tick this cycle, if at all.

        The returned timestamp is always an observed data time — see the
        module docstring for why wall clock is never used.
        """
        if not self._trackers:
            # Chopperless: one vacuous bootstrap tick as soon as a data
            # time exists, then periodic refreshes.
            if self._data_clock is None:
                return None
            if not self._ticked_once:
                logger.info("chopper_cascade bootstrap tick (no choppers)")
                return self._data_clock
            return self._refresh_tick()

        if not all(t.ready for t in self._trackers):
            self._logged_lock = False
            return None
        if not self._logged_lock:
            self._logged_lock = True
            logger.info(
                "chopper_cascade all locked: %s",
                [t.name for t in self._trackers],
            )
        if changed_at is not None:
            return changed_at
        return self._refresh_tick()

    def _refresh_tick(self) -> Timestamp | None:
        if self._cycle % self._refresh_every == 0:
            return self._data_clock
        return None

"""Workflow protocol + two-phase spec/factory registry.

Parity with reference ``workflows/workflow_factory.py``: the ``Workflow``
protocol (accumulate/finalize/clear, :21) and a registry with *two-phase*
registration (:178-268) — specs register at import time (cheap, declarative;
the dashboard needs them without heavy imports), factories attach later via
the ``SpecHandle`` when an instrument's compute modules load
(``Instrument.load_factories`` pattern, config/instrument.py:654).
"""

from __future__ import annotations

import threading
from collections.abc import Iterator, Mapping
from typing import Any, Callable, Protocol, runtime_checkable

from pydantic import BaseModel

from ..config.workflow_spec import WorkflowConfig, WorkflowId, WorkflowSpec
from ..utils.labeled import DataArray

__all__ = ["SpecHandle", "Workflow", "WorkflowFactory", "workflow_registry"]


@runtime_checkable
class Workflow(Protocol):
    """A streaming reduction: repeatedly fed per-window data, periodically
    finalized into named outputs."""

    def accumulate(self, data: Mapping[str, Any]) -> None:
        """Add one window of stream-name-keyed preprocessed data."""
        ...

    def finalize(self) -> dict[str, DataArray]:
        """Compute and return output-name-keyed results; clears window
        state but not cumulative state."""
        ...

    def clear(self) -> None:
        """Reset all state (run transition)."""
        ...


class SupportsContext(Protocol):
    def set_context(self, context: Mapping[str, Any]) -> None: ...


WorkflowFactoryFn = Callable[..., Workflow]


class SpecHandle:
    """Returned by register_spec; the hook for attaching the heavy factory
    later (two-phase registration, reference workflow_factory.py:80)."""

    def __init__(self, registry: WorkflowFactory, workflow_id: WorkflowId) -> None:
        self._registry = registry
        self._id = workflow_id

    @property
    def workflow_id(self) -> WorkflowId:
        return self._id

    def attach_factory(self, factory: WorkflowFactoryFn) -> WorkflowFactoryFn:
        """Attach the factory; usable as a decorator."""
        self._registry._attach(self._id, factory)
        return factory


class WorkflowFactory(Mapping[WorkflowId, WorkflowSpec]):
    """Registry of WorkflowSpecs + their factories.

    A factory is ``fn(*, source_name, params) -> Workflow`` (params is the
    validated pydantic model instance or None). Factories for specs with
    context_keys may accept a ``context`` kwarg delivering initial context.
    """

    def __init__(self) -> None:
        self._specs: dict[WorkflowId, WorkflowSpec] = {}
        self._factories: dict[WorkflowId, WorkflowFactoryFn] = {}
        self._lock = threading.RLock()

    # -- Mapping interface -------------------------------------------------
    def __getitem__(self, key: WorkflowId) -> WorkflowSpec:
        return self._specs[key]

    def __iter__(self) -> Iterator[WorkflowId]:
        return iter(self._specs)

    def __len__(self) -> int:
        return len(self._specs)

    # -- registration ------------------------------------------------------
    def register_spec(self, spec: WorkflowSpec) -> SpecHandle:
        with self._lock:
            wid = spec.identifier
            if wid in self._specs:
                raise ValueError(f"Duplicate workflow spec {wid}")
            self._specs[wid] = spec
            return SpecHandle(self, wid)

    def _attach(self, wid: WorkflowId, factory: WorkflowFactoryFn) -> None:
        with self._lock:
            if wid not in self._specs:
                raise ValueError(f"No spec registered for {wid}")
            if wid in self._factories:
                raise ValueError(f"Factory already attached for {wid}")
            self._factories[wid] = factory

    def has_factory(self, wid: WorkflowId) -> bool:
        return wid in self._factories

    def specs_for_instrument(self, instrument: str) -> list[WorkflowSpec]:
        return [s for s in self._specs.values() if s.instrument == instrument]

    # -- creation ----------------------------------------------------------
    def create(self, config: WorkflowConfig) -> Workflow:
        """Validate a start command against the spec and build the workflow."""
        wid = config.identifier
        try:
            spec = self._specs[wid]
        except KeyError as err:
            raise KeyError(f"Unknown workflow {wid}") from err
        source = config.job_id.source_name
        if spec.source_names and source not in spec.source_names:
            raise ValueError(
                f"Source {source!r} not valid for {wid}; expected one of "
                f"{spec.source_names}"
            )
        for aux_key, aux_source in config.aux_source_names.items():
            allowed = spec.aux_source_names.get(aux_key)
            if allowed is None:
                raise ValueError(f"Unknown aux source key {aux_key!r} for {wid}")
            if allowed and aux_source not in allowed:
                raise ValueError(
                    f"Aux source {aux_source!r} invalid for {aux_key!r} of {wid}"
                )
        params: BaseModel | None = spec.validate_params(config.params)
        try:
            factory = self._factories[wid]
        except KeyError as err:
            raise KeyError(
                f"Workflow {wid} has a spec but no attached factory — "
                "did the instrument's factories module load?"
            ) from err
        # Factories may opt in to the resolved aux bindings by declaring an
        # ``aux_source_names`` keyword (reference: workflow_factory.py
        # introspects factory signatures, :387-401).
        import inspect

        kwargs: dict[str, Any] = {"source_name": source, "params": params}
        sig = inspect.signature(factory)
        if "aux_source_names" in sig.parameters:
            kwargs["aux_source_names"] = dict(config.aux_source_names)
        return factory(**kwargs)

    def clear(self) -> None:
        """Testing hook: drop all registrations."""
        with self._lock:
            self._specs.clear()
            self._factories.clear()


workflow_registry = WorkflowFactory()
"""Process-wide default registry (reference: workflow_factory.py:157)."""

"""Streaming workflows: registry + concrete reductions.

Parity with reference ``src/ess/livedata/workflows/`` (SURVEY.md section
2.4), with the compute substrate swapped: where the reference wraps sciline
task graphs in ``StreamProcessorWorkflow`` and computes with scipp on CPU,
workflows here compose jitted JAX kernels with device-resident state —
"the pipeline" is a traced XLA program, not a Python DAG walked per cycle
(the reference itself found DAG-scheduler overhead significant,
core/sciline_scheduler.py:16-18; trace-once/execute-many removes it).
"""

from .workflow_factory import SpecHandle, Workflow, WorkflowFactory, workflow_registry

__all__ = ["SpecHandle", "Workflow", "WorkflowFactory", "workflow_registry"]

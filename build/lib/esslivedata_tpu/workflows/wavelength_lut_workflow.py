"""Wavelength lookup-table workflow.

Parity with reference ``workflows/wavelength_lut_workflow.py`` (which wraps
essreduce's analytical unwrap pipeline): recompute the TOF->wavelength
lookup table whenever the chopper cascade reaches new setpoints. The
synthetic ``chopper_cascade`` trigger (kafka/chopper_synthesizer.py) is the
primary dynamic stream — its *arrival* drives the recompute, its value is
ignored. Per-chopper rotation-speed and delay setpoints arrive as gated
context (ADR 0002: the job stays pending_context until every setpoint
stream has a value), so the table is only ever built from a complete,
locked cascade.

Outputs:

- ``wavelength_lut`` [distance, event_time_offset]: mean transmitted
  wavelength, with provenance coords (pulse period, stride, resolutions)
  making the published da00 self-describing.
- ``wavelength_bands`` [distance, event_time_offset]: the same estimate
  evaluated at the *exact* distances of source + each chopper + configured
  cut points (monitor/detector positions), so closely-spaced choppers stay
  individually resolved; an all-NaN row means that element blocks the beam.

The cascade propagation itself is host-side numpy (ops/chopper_cascade.py)
— a cold path that runs only on setpoint changes. The hot path consumes the
table as a device-side gather (monitor/detector wavelength modes).
"""

from __future__ import annotations

import logging
from collections.abc import Mapping, Sequence
from dataclasses import dataclass
from typing import Any

import numpy as np
from pydantic import BaseModel, Field

from ..config.chopper import (
    CHOPPER_CASCADE_SOURCE,
    delay_setpoint_stream,
    speed_setpoint_stream,
)
from ..core.constants import PULSE_PERIOD_NS_DEN, PULSE_PERIOD_NS_NUM
from ..ops.chopper_cascade import (
    DiskChopper,
    propagate_cascade,
    wavelength_band_at,
    wavelength_lut,
)
from ..utils.labeled import DataArray, Variable
from .workflow_factory import SpecHandle

logger = logging.getLogger(__name__)

__all__ = [
    "ChopperGeometry",
    "WavelengthLutParams",
    "WavelengthLutWorkflow",
    "attach_wavelength_lut_factory",
]


@dataclass(frozen=True)
class ChopperGeometry:
    """Static per-chopper geometry (from the instrument's NeXus artifact);
    the live quantities (rotation speed, delay) arrive as context streams."""

    name: str
    distance_m: float
    slit_edges_deg: tuple[tuple[float, float], ...] = ((0.0, 180.0),)


class WavelengthLutParams(BaseModel):
    """User-facing parameters of the LUT computation (the UI schema)."""

    pulse_period_ns: float = PULSE_PERIOD_NS_NUM / PULSE_PERIOD_NS_DEN
    pulse_length_ns: float = 2.86e6
    stride: int = Field(default=1, ge=1)
    distance_start_m: float = 1.0
    distance_stop_m: float = 50.0
    distance_resolution_m: float = Field(default=1.0, gt=0)
    n_time_bins: int = Field(default=500, ge=2)
    wavelength_min_a: float = Field(default=0.1, gt=0)
    wavelength_max_a: float = 25.0
    cut_distances_m: list[float] = Field(default_factory=list)
    """Extra exact-distance rows for the bands output (typically monitor
    and detector positions)."""


class WavelengthLutWorkflow:
    """Workflow: cascade trigger + setpoint context -> LUT DataArrays."""

    def __init__(
        self,
        *,
        choppers: Sequence[ChopperGeometry],
        params: WavelengthLutParams | None = None,
    ) -> None:
        self._choppers = list(choppers)
        self._params = params or WavelengthLutParams()
        self._speed: dict[str, float] = {}
        self._delay: dict[str, float] = {}
        self._triggered = False
        self._computed_signature: tuple[float, ...] | None = None

    # -- context ----------------------------------------------------------
    def set_context(self, context: Mapping[str, Any]) -> None:
        """Latest sample of each setpoint NXlog series wins."""
        for geo in self._choppers:
            if (value := _latest(context.get(speed_setpoint_stream(geo.name)))) is not None:
                self._speed[geo.name] = value
            if (value := _latest(context.get(delay_setpoint_stream(geo.name)))) is not None:
                self._delay[geo.name] = value

    # -- Workflow protocol -------------------------------------------------
    def accumulate(self, data: Mapping[str, Any]) -> None:
        if CHOPPER_CASCADE_SOURCE in data:
            self._triggered = True

    def finalize(self) -> dict[str, DataArray]:
        if not self._triggered:
            return {}
        missing = [
            g.name
            for g in self._choppers
            if g.name not in self._speed or g.name not in self._delay
        ]
        if missing:
            # Trigger arrived before context (gating should prevent this);
            # stay triggered so the next finalize retries.
            return {}
        parked = [g.name for g in self._choppers if self._speed[g.name] <= 0]
        if parked:
            # A parked chopper (speed setpoint 0) has no well-defined open
            # windows; skip the recompute — stay triggered so an updated
            # speed retries — rather than erroring the job permanently.
            logger.warning(
                "wavelength LUT recompute skipped: chopper(s) %s parked "
                "(speed setpoint <= 0)",
                parked,
            )
            return {}
        signature = self._signature()
        if signature == self._computed_signature:
            # Refresh tick with unchanged setpoints (the synthesizer
            # re-emits periodically so late-started jobs prime): no-op.
            self._triggered = False
            return {}
        self._triggered = False
        out = self._compute()
        self._computed_signature = signature
        return out

    def _signature(self) -> tuple[float, ...]:
        return tuple(
            v
            for g in self._choppers
            for v in (self._speed[g.name], self._delay[g.name])
        )

    def clear(self) -> None:
        self._triggered = False
        self._computed_signature = None

    # -- computation -------------------------------------------------------
    def _disk_choppers(self) -> list[DiskChopper]:
        return [
            DiskChopper(
                name=g.name,
                distance_m=g.distance_m,
                frequency_hz=self._speed[g.name],
                delay_ns=self._delay[g.name],
                slit_edges_deg=g.slit_edges_deg,
            )
            for g in self._choppers
        ]

    def _compute(self) -> dict[str, DataArray]:
        p = self._params
        frame_period = p.pulse_period_ns * p.stride
        subframes = propagate_cascade(
            self._disk_choppers(),
            pulse_period_ns=p.pulse_period_ns,
            pulse_length_ns=p.pulse_length_ns,
            wavelength_min_a=p.wavelength_min_a,
            wavelength_max_a=p.wavelength_max_a,
            stride=p.stride,
        )
        n_distance = (
            int(
                np.ceil(
                    (p.distance_stop_m - p.distance_start_m)
                    / p.distance_resolution_m
                )
            )
            + 1
        )
        distances = np.linspace(p.distance_start_m, p.distance_stop_m, n_distance)
        table, time_edges = wavelength_lut(
            subframes,
            distances_m=distances,
            frame_period_ns=frame_period,
            n_time_bins=p.n_time_bins,
        )
        # Exact-distance diagnostic rows: source + choppers + cut points.
        band_distances = np.array(
            sorted(
                {0.0}
                | {g.distance_m for g in self._choppers}
                | set(p.cut_distances_m)
            )
        )
        bands = np.stack(
            [
                wavelength_band_at(
                    subframes,
                    d,
                    frame_period_ns=frame_period,
                    time_edges_ns=time_edges,
                )
                for d in band_distances
            ]
        )
        provenance = {
            "pulse_period": Variable(np.asarray(p.pulse_period_ns), (), "ns"),
            "pulse_stride": Variable(np.asarray(p.stride), (), None),
            "distance_resolution": Variable(
                np.asarray(p.distance_resolution_m), (), "m"
            ),
        }
        time_coord = Variable(time_edges, ("event_time_offset",), "ns")
        return {
            "wavelength_lut": DataArray(
                Variable(table, ("distance", "event_time_offset"), "angstrom"),
                coords={
                    "distance": Variable(distances, ("distance",), "m"),
                    "event_time_offset": time_coord,
                    **provenance,
                },
                name="wavelength_lut",
            ),
            "wavelength_bands": DataArray(
                Variable(bands, ("distance", "event_time_offset"), "angstrom"),
                coords={
                    "distance": Variable(band_distances, ("distance",), "m"),
                    "event_time_offset": time_coord,
                    **provenance,
                },
                name="wavelength_bands",
            ),
        }


def _latest(series: Any) -> float | None:
    """Latest sample of an accumulated NXlog series (or scalar), or None."""
    if series is None:
        return None
    if isinstance(series, DataArray):
        values = np.atleast_1d(np.asarray(series.data.values))
        return float(values[-1]) if values.size else None
    values = np.atleast_1d(np.asarray(getattr(series, "value", series)))
    return float(values[-1]) if values.size else None


def attach_wavelength_lut_factory(
    handle: SpecHandle, *, choppers: Sequence[ChopperGeometry]
) -> None:
    """Attach the LUT factory to a registered spec handle.

    The spec must declare ``context_keys`` covering every chopper's
    speed/delay setpoint stream (``spec_context_keys`` builds that list) so
    the JobManager gates the job pending_context until the cascade is fully
    locked — the reference enforces the same invariant by sharing sciline
    key objects between bindings and provider (its :382-391).
    """

    @handle.attach_factory
    def _factory(*, source_name: str, params) -> WavelengthLutWorkflow:  # noqa: ARG001
        return WavelengthLutWorkflow(choppers=choppers, params=params)


def spec_context_keys(choppers: Sequence[ChopperGeometry]) -> list[str]:
    """The context streams a LUT spec must gate on for these choppers."""
    keys: list[str] = []
    for g in choppers:
        keys.append(speed_setpoint_stream(g.name))
        keys.append(delay_setpoint_stream(g.name))
    return keys

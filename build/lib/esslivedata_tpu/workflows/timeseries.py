"""Timeseries workflow: republish the accumulated NXlog series
(reference: workflows/timeseries.py:12 TimeseriesStreamProcessor)."""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

from ..utils.labeled import DataArray

__all__ = ["TimeseriesWorkflow"]


class TimeseriesWorkflow:
    """Passes through the latest accumulated log DataArray per stream."""

    def __init__(self) -> None:
        self._latest: dict[str, DataArray] = {}

    def accumulate(self, data: Mapping[str, Any]) -> None:
        for key, value in data.items():
            if isinstance(value, DataArray):
                self._latest[key] = value

    def finalize(self) -> dict[str, DataArray]:
        out = dict(self._latest)
        return out

    def clear(self) -> None:
        self._latest.clear()

"""Area-detector (camera) view: ad00 images with current+cumulative outputs
and an optional logical transform (reference: workflows/area_detector_view.py:22).
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np
from pydantic import BaseModel, ConfigDict

from ..core.timestamp import Timestamp
from ..preprocessors.accumulators import WindowedCumulative
from ..utils.labeled import DataArray, Variable

__all__ = ["AreaDetectorParams", "AreaDetectorView"]


class AreaDetectorParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    transpose: bool = False
    flip_y: bool = False
    flip_x: bool = False


class AreaDetectorView:
    """Accumulates 2-D camera frames through the paired window/cumulative
    accumulator: both views restart automatically when the frame's
    structure changes (camera ROI reconfigured upstream, unit change)."""

    def __init__(self, *, params: AreaDetectorParams | None = None) -> None:
        self._params = params or AreaDetectorParams()
        self._acc = WindowedCumulative()

    def _transform(self, values: np.ndarray) -> np.ndarray:
        p = self._params
        if p.transpose:
            values = values.T
        if p.flip_y:
            values = values[::-1, :]
        if p.flip_x:
            values = values[:, ::-1]
        return values

    def accumulate(self, data: Mapping[str, Any]) -> None:
        for value in data.values():
            if not isinstance(value, DataArray) or value.data.ndim != 2:
                continue
            frame = self._transform(
                np.asarray(value.values, dtype=np.float64)
            )
            ny, nx = frame.shape
            self._acc.add(
                Timestamp.from_ns(0),
                DataArray(
                    Variable(frame, ("y", "x"), value.unit),
                    coords={
                        "y": Variable(
                            np.arange(ny, dtype=np.float64), ("y",), ""
                        ),
                        "x": Variable(
                            np.arange(nx, dtype=np.float64), ("x",), ""
                        ),
                    },
                    name="frame",
                ),
            )

    def finalize(self) -> dict[str, DataArray]:
        if self._acc.is_empty:
            return {}
        window, cumulative = self._acc.take()
        window.name = "current"
        cumulative.name = "cumulative"
        return {"current": window, "cumulative": cumulative}

    def clear(self) -> None:
        self._acc.clear()

"""Projection tables: physical pixel -> 2-D screen bin.

The reference computes per-event screen coordinates with numpy fancy
indexing per batch (GeometricProjector, projectors.py:47-100, chosen over
sc.bins_like for 2-10x speed). On TPU the projection is hoisted out of the
per-batch path entirely: geometry is compiled *once* into an int32 gather
table ``lut[replica, pixel] -> flat screen bin`` and per-batch work is a
single device gather fused into the scatter kernel. Position-noise replicas
(the reference's gaussian antialiasing of coarse pixels onto fine screens)
are extra LUT rows at 1/R weight.

Geometry recompute (moved detector, new noise draw) = rebuild the table on
host and swap it in — the stream never stalls (SURVEY.md section 7 "hard
parts" item 5).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ...utils.labeled import Variable

__all__ = [
    "LogicalView",
    "NdLogicalView",
    "ProjectionTable",
    "project_geometric",
    "project_logical",
    "project_logical_nd",
]


@dataclass(frozen=True)
class ProjectionTable:
    """Pixel -> screen-bin gather table plus screen geometry."""

    lut: np.ndarray  # int32 [n_replica, n_pixel_id_space] -> flat bin or -1
    ny: int
    nx: int
    y_edges: Variable
    x_edges: Variable
    x_name: str = "x"
    y_name: str = "y"

    @property
    def n_screen(self) -> int:
        return self.ny * self.nx

    @property
    def n_replica(self) -> int:
        return int(self.lut.shape[0])


def _bin_2d(
    xc: np.ndarray,
    yc: np.ndarray,
    x_edges: np.ndarray,
    y_edges: np.ndarray,
    nx: int,
    ny: int,
) -> np.ndarray:
    xi = np.searchsorted(x_edges, xc, side="right") - 1
    yi = np.searchsorted(y_edges, yc, side="right") - 1
    ok = (xi >= 0) & (xi < nx) & (yi >= 0) & (yi < ny)
    flat = np.where(ok, yi * nx + xi, -1).astype(np.int32)
    return flat


def project_geometric(
    positions: np.ndarray,
    pixel_ids: np.ndarray,
    *,
    mode: str = "xy_plane",
    resolution: tuple[int, int] = (128, 128),
    noise_sigma: float = 0.0,
    n_replica: int = 1,
    extent: tuple[float, float, float, float] | None = None,
    seed: int = 0,
    unit: str = "m",
) -> ProjectionTable:
    """Build a projection table from 3-D pixel positions.

    Parameters
    ----------
    positions:
        [n, 3] pixel centers (x, y, z).
    pixel_ids:
        [n] detector numbers addressing events' pixel_id space.
    mode:
        'xy_plane' — project along z onto the xy plane;
        'cylinder_mantle_z' — unroll a cylinder around z: (phi*r_mean, z).
    resolution:
        (ny, nx) screen bins.
    noise_sigma:
        Gaussian position noise in position units; with ``n_replica`` > 1
        each pixel gets R jittered screen assignments at weight 1/R,
        antialiasing coarse pixels onto fine screens (reference
        projectors.py:47 replicas).
    extent:
        Optional (x_min, x_max, y_min, y_max) screen bounds; default = data
        bounds of the *unjittered* projection.
    """
    positions = np.asarray(positions, dtype=np.float64)
    pixel_ids = np.asarray(pixel_ids)
    if positions.ndim != 2 or positions.shape[1] != 3:
        raise ValueError("positions must be [n, 3]")
    if positions.shape[0] != pixel_ids.shape[0]:
        raise ValueError("positions and pixel_ids must have equal length")
    ny, nx = resolution

    def project(pos: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        if mode == "xy_plane":
            return pos[:, 0], pos[:, 1]
        if mode == "cylinder_mantle_z":
            r = np.hypot(pos[:, 0], pos[:, 1])
            phi = np.arctan2(pos[:, 1], pos[:, 0])
            return phi * float(np.mean(r)), pos[:, 2]
        raise ValueError(f"Unknown projection mode {mode!r}")

    x0, y0 = project(positions)
    if extent is None:
        pad_x = (x0.max() - x0.min()) / nx if x0.max() > x0.min() else 1.0
        pad_y = (y0.max() - y0.min()) / ny if y0.max() > y0.min() else 1.0
        extent = (
            float(x0.min() - 0.5 * pad_x),
            float(x0.max() + 0.5 * pad_x),
            float(y0.min() - 0.5 * pad_y),
            float(y0.max() + 0.5 * pad_y),
        )
    x_edges = np.linspace(extent[0], extent[1], nx + 1)
    y_edges = np.linspace(extent[2], extent[3], ny + 1)

    n_id_space = int(pixel_ids.max()) + 1
    rng = np.random.default_rng(seed)
    if noise_sigma > 0.0 and n_replica > 1:
        luts = []
        for _ in range(n_replica):
            jitter = rng.normal(0.0, noise_sigma, positions.shape)
            xj, yj = project(positions + jitter)
            luts.append(_bin_2d(xj, yj, x_edges, y_edges, nx, ny))
        flat_rep = np.stack(luts)  # [R, n]
    else:
        flat_rep = _bin_2d(x0, y0, x_edges, y_edges, nx, ny)[None, :]

    lut = np.full((flat_rep.shape[0], n_id_space), -1, dtype=np.int32)
    lut[:, pixel_ids] = flat_rep
    return ProjectionTable(
        lut=lut,
        ny=ny,
        nx=nx,
        y_edges=Variable(y_edges, ("y",), unit),
        x_edges=Variable(x_edges, ("x",), unit),
    )


@dataclass(frozen=True)
class NdLogicalView:
    """N-d fold -> slice -> display spec for voxel detectors (DREAM).

    The reference expresses these as scipp fold/transpose/slice/flatten
    transforms re-applied per cycle (dream/views.py); here the whole view
    collapses into the pixel->screen LUT built once: ``sizes`` folds the
    flat detector_number array, ``select`` slices dims to a fixed index
    (other voxels drop out), ``y``/``x`` dims composite into screen
    rows/cols, and any remaining dim is summed — many voxels landing on one
    screen bin, which the scatter-add performs for free.
    """

    sizes: dict[str, int]
    y: tuple[str, ...]
    x: tuple[str, ...] = ()
    select: dict[str, int] = None  # type: ignore[assignment]

    def __post_init__(self) -> None:
        object.__setattr__(self, "select", dict(self.select or {}))
        names = set(self.sizes)
        for dim in (*self.y, *self.x, *self.select):
            if dim not in names:
                raise ValueError(f"view dim {dim!r} not in sizes {names}")
        if set(self.y) & set(self.x):
            raise ValueError("y and x dims must be disjoint")
        for dim, index in self.select.items():
            if not 0 <= index < self.sizes[dim]:
                raise ValueError(
                    f"select[{dim!r}]={index} out of range {self.sizes[dim]}"
                )


def project_logical_nd(
    detector_numbers: np.ndarray, view: NdLogicalView
) -> ProjectionTable:
    """Build a projection table from an N-d voxel layout.

    ``detector_numbers`` is flat (C-order over ``view.sizes``) or already
    shaped to those sizes.
    """
    shape = tuple(view.sizes.values())
    det = np.asarray(detector_numbers).reshape(shape)
    dims = list(view.sizes)
    index = np.indices(shape)
    per_dim = {d: index[i] for i, d in enumerate(dims)}

    keep = np.ones(shape, dtype=bool)
    for dim, sel in view.select.items():
        keep &= per_dim[dim] == sel

    def composite(parts: tuple[str, ...]) -> tuple[np.ndarray, int]:
        idx = np.zeros(shape, dtype=np.int64)
        total = 1
        for dim in parts:
            idx = idx * view.sizes[dim] + per_dim[dim]
            total *= view.sizes[dim]
        return idx, total

    row, ny = composite(view.y)
    col, nx = composite(view.x)
    screen = np.where(keep, row * nx + col, -1).astype(np.int32)

    n_id_space = int(det.max()) + 1
    lut = np.full((1, n_id_space), -1, dtype=np.int32)
    lut[0, det.reshape(-1)] = screen.reshape(-1)
    return ProjectionTable(
        lut=lut,
        ny=ny,
        nx=nx,
        y_edges=Variable(np.arange(ny + 1, dtype=np.float64) - 0.5, ("y",), ""),
        x_edges=Variable(np.arange(nx + 1, dtype=np.float64) - 0.5, ("x",), ""),
    )


@dataclass(frozen=True)
class LogicalView:
    """Fold/transpose/slice spec for detectors whose detector_number layout
    is already a grid (reference LogicalProjector: fold/slice/sum)."""

    fold: tuple[int, int]  # (ny, nx)
    transpose: bool = False
    flip_y: bool = False
    flip_x: bool = False


def project_logical(
    detector_numbers: np.ndarray,
    view: LogicalView | None = None,
) -> ProjectionTable:
    """Build a projection table from a 2-D detector_number grid.

    ``detector_numbers`` is the instrument's [ny, nx] grid (or flat array
    with ``view.fold``). Screen bin (y, x) simply *is* the grid position —
    the identity-layout fast path the reference implements as fold/slice
    transforms.
    """
    det = np.asarray(detector_numbers)
    if det.ndim == 1:
        if view is None:
            raise ValueError("flat detector_numbers require a LogicalView.fold")
        det = det.reshape(view.fold)
    if view is not None:
        if view.transpose:
            det = det.T
        if view.flip_y:
            det = det[::-1, :]
        if view.flip_x:
            det = det[:, ::-1]
    ny, nx = det.shape
    n_id_space = int(det.max()) + 1
    lut = np.full((1, n_id_space), -1, dtype=np.int32)
    yy, xx = np.meshgrid(np.arange(ny), np.arange(nx), indexing="ij")
    lut[0, det.reshape(-1)] = (yy * nx + xx).reshape(-1).astype(np.int32)
    return ProjectionTable(
        lut=lut,
        ny=ny,
        nx=nx,
        y_edges=Variable(np.arange(ny + 1, dtype=np.float64) - 0.5, ("y",), ""),
        x_edges=Variable(np.arange(nx + 1, dtype=np.float64) - 0.5, ("x",), ""),
    )

"""2-D detector view — the flagship workflow (reference: workflows/
detector_view/, 2100 LoC; SURVEY.md section 2.4).

Projects physical detector pixels onto a 2-D screen, histograms events over
screen x TOA on device, and derives image / spectrum / counts / ROI-spectra
outputs. TPU shape of the reference design:

- GeometricProjector's per-pixel screen coords with gaussian position-noise
  replicas (projectors.py:47-95) become a precomputed [replica, pixel] ->
  screen-bin int32 gather table built once per geometry (host, numpy).
- The event projection + per-pixel grouping + histogramming chain is one
  jitted scatter-add into a [screen, toa] HBM-resident state pair.
- ROI spectra (roi.py:188) are a mask matmul [n_roi, screen] @ [screen,
  toa] on the MXU, recomputed every finalize at negligible cost.
"""

from .projectors import LogicalView, ProjectionTable, project_geometric, project_logical
from .workflow import DetectorViewParams, DetectorViewWorkflow

__all__ = [
    "DetectorViewParams",
    "DetectorViewWorkflow",
    "LogicalView",
    "ProjectionTable",
    "project_geometric",
    "project_logical",
]

"""Dashboard grid templates: data model + raw YAML loading.

Parity with reference ``config/grid_template.py`` (GridSpec, raw template
loading from each instrument package's ``grid_templates/`` directory). The
dashboard's plot orchestrator materialises these specs into live grids;
persisting a configured grid round-trips through the same model.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from importlib import resources
from typing import Any

import yaml

__all__ = [
    "CellGeometry",
    "GridCellSpec",
    "GridSpec",
    "load_grid_templates",
    "load_raw_grid_templates",
]

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class CellGeometry:
    """Placement of one cell on the grid."""

    row: int
    col: int
    row_span: int = 1
    col_span: int = 1

    def __post_init__(self) -> None:
        if self.row < 0 or self.col < 0:
            raise ValueError("cell position must be non-negative")
        if self.row_span < 1 or self.col_span < 1:
            raise ValueError("cell span must be >= 1")


@dataclass(frozen=True)
class GridCellSpec:
    """One plot cell: where it sits and what it shows.

    ``workflow``/``output`` select the result stream (matched against
    ResultKeys); ``plotter`` optionally forces a plotter kind, else the
    registry auto-selects from the output's template array.
    """

    geometry: CellGeometry
    workflow: str = ""
    output: str = ""
    source: str = ""
    plotter: str = ""
    title: str = ""
    # Presentation parameters (dashboard.plots.PlotParams schema: scale,
    # cmap, vmin, vmax) — carried opaquely here so templates/persistence
    # stay decoupled from the rendering layer's knob set.
    params: tuple[tuple[str, Any], ...] = ()

    @property
    def params_dict(self) -> dict[str, Any]:
        return dict(self.params)

    @staticmethod
    def freeze_params(raw: dict[str, Any] | None) -> tuple[tuple[str, Any], ...]:
        return tuple(sorted((raw or {}).items()))


@dataclass(frozen=True)
class GridSpec:
    """Grid configuration without runtime state (templates, persistence)."""

    name: str
    title: str = ""
    description: str = ""
    nrows: int = 2
    ncols: int = 2
    cells: tuple[GridCellSpec, ...] = field(default_factory=tuple)
    enabled: bool = True

    @property
    def min_rows(self) -> int:
        if not self.cells:
            return self.nrows
        return max(c.geometry.row + c.geometry.row_span for c in self.cells)

    @property
    def min_cols(self) -> int:
        if not self.cells:
            return self.ncols
        return max(c.geometry.col + c.geometry.col_span for c in self.cells)

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "GridSpec":
        cells = tuple(
            GridCellSpec(
                geometry=CellGeometry(**cell.get("geometry", {"row": 0, "col": 0})),
                workflow=cell.get("workflow", ""),
                output=cell.get("output", ""),
                source=cell.get("source", ""),
                plotter=cell.get("plotter", ""),
                title=cell.get("title", ""),
                params=GridCellSpec.freeze_params(cell.get("params")),
            )
            for cell in raw.get("cells", [])
        )
        return cls(
            name=raw["name"],
            title=raw.get("title", raw["name"]),
            description=raw.get("description", ""),
            nrows=raw.get("nrows", 2),
            ncols=raw.get("ncols", 2),
            cells=cells,
            enabled=raw.get("enabled", True),
        )


def load_raw_grid_templates(instrument: str) -> list[dict[str, Any]]:
    """Raw grid template dicts from the instrument package, unvalidated."""
    templates: list[dict[str, Any]] = []
    try:
        package = f"esslivedata_tpu.config.instruments.{instrument}"
        templates_dir = resources.files(package).joinpath("grid_templates")
        if not templates_dir.is_dir():
            return templates
        for item in templates_dir.iterdir():
            if item.is_file() and item.name.endswith(".yaml"):
                try:
                    raw = yaml.safe_load(item.read_text())
                except Exception:
                    logger.exception("Failed to load template %s", item.name)
                    continue
                if isinstance(raw, dict):
                    templates.append(raw)
                else:
                    logger.warning("Template %s is not a dict", item.name)
    except ModuleNotFoundError:
        logger.warning("Instrument package not found: %s", instrument)
    return templates


def load_grid_templates(instrument: str) -> list[GridSpec]:
    """Validated GridSpecs for an instrument; malformed templates skipped."""
    specs: list[GridSpec] = []
    for raw in load_raw_grid_templates(instrument):
        try:
            specs.append(GridSpec.from_dict(raw))
        except Exception:
            logger.exception(
                "Malformed grid template %r for %s", raw.get("name"), instrument
            )
    return specs

"""ROI stream naming conventions shared by backend and dashboard.

Parity with reference ``config/roi_names.py``: ROIs are identified by a
global integer index (da00 carries no strings), partitioned by geometry
type. The mapper renders the stable readback/spectra output keys and the
per-index display names both sides agree on, so the dashboard can label
``roi_spectra`` rows and match readbacks to the shapes the user drew.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Literal

from .models import PolygonROI, RectangleROI

__all__ = ["ROIGeometry", "ROIStreamMapper", "default_roi_mapper"]

ROIGeometryType = Literal["rectangle", "polygon"]


@dataclass(frozen=True, slots=True)
class ROIGeometry:
    """One geometry type's slice of the global ROI index space."""

    geometry_type: ROIGeometryType
    num_rois: int
    index_offset: int = 0

    @property
    def readback_key(self) -> str:
        """Output name carrying applied-ROI readback for this geometry."""
        return f"roi_{self.geometry_type}"

    @property
    def index_range(self) -> range:
        return range(self.index_offset, self.index_offset + self.num_rois)

    @property
    def roi_class(self) -> type[RectangleROI] | type[PolygonROI]:
        if self.geometry_type == "rectangle":
            return RectangleROI
        if self.geometry_type == "polygon":
            return PolygonROI
        raise ValueError(f"Unknown geometry type: {self.geometry_type}")

    def display_name(self, index: int) -> str:
        """Stable user-facing label for a global ROI index of this type."""
        if index not in self.index_range:
            raise IndexError(f"index {index} outside {self.index_range}")
        return f"{self.geometry_type}_{index - self.index_offset}"


class ROIStreamMapper:
    """Allocates the global ROI index space across geometry types."""

    def __init__(self, geometries: tuple[ROIGeometry, ...] | None = None) -> None:
        if geometries is None:
            geometries = (
                ROIGeometry(geometry_type="rectangle", num_rois=4, index_offset=0),
                ROIGeometry(geometry_type="polygon", num_rois=4, index_offset=4),
            )
        self.geometries = geometries
        offsets = sorted(
            (g.index_offset, g.index_offset + g.num_rois) for g in geometries
        )
        for (_, prev_end), (start, _) in zip(offsets, offsets[1:], strict=False):
            if start < prev_end:
                raise ValueError("ROI index ranges overlap")

    @property
    def total_rois(self) -> int:
        return sum(g.num_rois for g in self.geometries)

    def geometry_for(self, index: int) -> ROIGeometry:
        for g in self.geometries:
            if index in g.index_range:
                return g
        raise IndexError(f"No geometry owns ROI index {index}")

    def display_name(self, index: int) -> str:
        return self.geometry_for(index).display_name(index)

    def readback_keys(self) -> list[str]:
        return [g.readback_key for g in self.geometries]


def default_roi_mapper() -> ROIStreamMapper:
    return ROIStreamMapper()

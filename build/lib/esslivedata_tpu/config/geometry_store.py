"""Geometry-artifact registry: dated NeXus files, cached, date-resolved.

Mirrors the reference's geometry pipeline
(preprocessors/detector_data.py:66-127): every instrument has one or more
geometry files named ``geometry-<instrument>-<YYYY-MM-DD>.nxs``, the date
being the start of the file's validity window; the file applying at a
given date is the newest one whose date is not after it. Files land in a
cache directory, overridable with ``LIVEDATA_DATA_DIR`` (an operator can
drop a hand-built artifact there and it wins over the registry).

Where the reference *downloads* artifacts with pooch, this environment has
no egress, so a cache miss *synthesizes* the file from the instrument's
declarative NeXus plan (``nexus_plans.py``). The consumer contract is
byte-for-byte the same — a real ESS file copied into the cache is used
as-is.
"""

from __future__ import annotations

import datetime as _dt
import logging
import os
import re
from pathlib import Path

import numpy as np

__all__ = [
    "GEOMETRY_REGISTRY",
    "data_dir",
    "geometry_filename",
    "geometry_path",
    "load_detector_geometry",
    "load_logical_layout",
]

logger = logging.getLogger(__name__)

#: filename -> None (synthesized) or an expected md5 of a pinned real
#: artifact. Multiple dated entries per instrument express validity
#: windows; files are never replaced in place (new date = new file).
GEOMETRY_REGISTRY: dict[str, str | None] = {
    "geometry-loki-2026-01-01.nxs": None,
    "geometry-dream-2026-01-01.nxs": None,
    "geometry-bifrost-2026-01-01.nxs": None,
    "geometry-estia-2026-01-01.nxs": None,
    "geometry-nmx-2026-01-01.nxs": None,
    "geometry-odin-2026-01-01.nxs": None,
    "geometry-tbl-2026-01-01.nxs": None,
    "geometry-dummy-2026-01-01.nxs": None,
}

def _name_pattern(instrument: str) -> re.Pattern:
    """Exact-match pattern for one instrument's dated artifacts: anchored,
    so 'dummy' never matches an operator-installed 'dummy-hr' file."""
    return re.compile(
        rf"^geometry-{re.escape(instrument)}-(\d{{4}}-\d{{2}}-\d{{2}})\.nxs$"
    )


def data_dir() -> Path:
    """The geometry data directory (LIVEDATA_DATA_DIR or the scratch
    default) — where artifacts are cached, and where operators drop
    hand-built dated files (they join date resolution automatically)."""
    return _cache_dir()


def _cache_dir() -> Path:
    override = os.environ.get("LIVEDATA_DATA_DIR")
    if override:
        return Path(override)
    # Per-user cache (XDG), mode 0o700: a world-scratch default would let
    # another local user pre-plant artifacts the loader silently trusts.
    try:
        xdg = os.environ.get("XDG_CACHE_HOME")
        # Path.home() raises RuntimeError for a UID with no passwd entry
        # (common in containers) — that case takes the fallback too.
        base = Path(xdg) if xdg else Path.home() / ".cache"
        target = base / "esslivedata-tpu" / "geometry"
        target.mkdir(parents=True, exist_ok=True, mode=0o700)
        return target
    except (OSError, RuntimeError):
        import tempfile

        fallback = Path(tempfile.gettempdir()) / "esslivedata-tpu" / "geometry"
        logger.warning(
            "No usable per-user cache; falling back to world scratch %s "
            "— set LIVEDATA_DATA_DIR for a trusted location",
            fallback,
        )
        fallback.mkdir(parents=True, exist_ok=True, mode=0o700)
        return fallback


def geometry_filename(
    instrument: str, date: _dt.date | None = None
) -> str:
    """The registry filename valid at ``date`` (default: today).

    The newest entry whose embedded date is <= ``date`` wins — identical
    date-LUT semantics to the reference's ``get_nexus_geometry_filename``.
    """
    date = date or _dt.date.today()
    # Registry entries plus any dated files an operator dropped into the
    # data directory (scripts/fetch_geometry.py install): both join date
    # resolution, so installing a new artifact needs no code change.
    names = set(GEOMETRY_REGISTRY)
    try:
        names.update(p.name for p in _cache_dir().glob("geometry-*.nxs"))
    except OSError:  # pragma: no cover - unreadable data dir
        pass
    pattern = _name_pattern(instrument)
    candidates: list[tuple[_dt.date, str]] = []
    for name in names:
        m = pattern.match(name)
        if not m:
            continue
        candidates.append((_dt.date.fromisoformat(m.group(1)), name))
    if not candidates:
        raise ValueError(f"No geometry files registered for {instrument!r}")
    candidates.sort()
    valid = [name for d, name in candidates if d <= date]
    if not valid:
        raise ValueError(
            f"No geometry file for {instrument!r} valid at {date} "
            f"(earliest is {candidates[0][0]})"
        )
    return valid[-1]


def geometry_path(
    instrument: str, date: _dt.date | None = None
) -> Path:
    """Resolve (and materialize if needed) the geometry artifact path."""
    name = geometry_filename(instrument, date)
    path = _cache_dir() / name
    if path.exists():
        _verify_pin(path, name)
        return path
    if GEOMETRY_REGISTRY.get(name) is not None:
        # A pinned entry names a specific real artifact; synthesizing a
        # local stand-in under that name would hand the consumer wrong
        # geometry once and then fail the pin check forever after.
        raise ValueError(
            f"Geometry artifact {name} is pinned in the registry but not "
            f"present in {path.parent}; install it with "
            f"scripts/fetch_geometry.py"
        )
    import os as _os
    import tempfile

    from .nexus_plans import plan_for
    from .nexus_synthesis import write_nexus

    logger.info("Synthesizing geometry artifact %s", path)
    # Unique temp file per writer: several services resolving the same
    # missing artifact concurrently must not truncate each other mid-write;
    # whichever finishes last atomically installs a *complete* file.
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp_name = tempfile.mkstemp(
        dir=path.parent, prefix=path.name, suffix=".partial"
    )
    _os.close(fd)
    tmp = Path(tmp_name)
    try:
        write_nexus(plan_for(instrument), tmp)
        tmp.replace(path)
    finally:
        tmp.unlink(missing_ok=True)
    return path


def _verify_pin(path: Path, name: str) -> None:
    """Check a cached file against its registry md5 pin, when one exists.

    Synthesized entries (pin None) and operator-dropped files outside the
    registry are trusted as-is — the pin protects exactly the case where a
    known real artifact could have been swapped in the cache.
    """
    expected = GEOMETRY_REGISTRY.get(name)
    if expected is None:
        return
    import hashlib

    digest = hashlib.md5(path.read_bytes()).hexdigest()
    if digest != expected:
        raise ValueError(
            f"Geometry artifact {path} fails its registry pin "
            f"(md5 {digest} != {expected}); delete the cached file or fix "
            f"the registry entry"
        )


def load_detector_geometry(
    path: str | Path, bank: str
) -> tuple[np.ndarray, np.ndarray]:
    """(positions [n, 3] metres, pixel ids [n]) of a geometric bank."""
    import h5py

    with h5py.File(path, "r") as f:
        det = f[f"/entry/instrument/{bank}"]
        ids = np.asarray(det["detector_number"]).reshape(-1)
        xyz = [
            np.asarray(det[k], dtype=np.float64).reshape(-1)
            for k in ("x_pixel_offset", "y_pixel_offset", "z_pixel_offset")
        ]
    return np.stack(xyz, axis=1), ids


def load_logical_layout(path: str | Path, bank: str) -> np.ndarray:
    """The N-d ``detector_number`` layout of a logical bank."""
    import h5py

    with h5py.File(path, "r") as f:
        return np.asarray(f[f"/entry/instrument/{bank}/detector_number"])

"""Infrastructure YAML config loading (reference: config/config_loader.py:26).

Config files live in ``config/defaults/`` and are selected by namespace +
environment: ``<namespace>_<env>.yaml`` (plain YAML) or
``<namespace>_<env>.yaml.jinja`` (Jinja2 template whose undeclared
variables are filled from environment variables — missing ones raise).
"""

from __future__ import annotations

import os
from importlib import resources

import yaml

from .env import DEFAULT_ENV, ENV_VAR

__all__ = ["load_config"]


def _template_env_vars(template_content: str) -> dict[str, str]:
    import json

    from jinja2 import Environment
    from jinja2.meta import find_undeclared_variables

    env = Environment(autoescape=True)
    variables = find_undeclared_variables(env.parse(template_content))
    values: dict[str, str] = {}
    for var in variables:
        value = os.getenv(var)
        if value is None:
            raise ValueError(
                f"Environment variable {var} required by config template "
                "is not set"
            )
        # YAML-safe: substituted unquoted, a credential containing '#',
        # ': ' or leading flow characters would corrupt the parse (or be
        # silently truncated at a comment). JSON strings are valid YAML.
        values[var] = json.dumps(value)
    return values


def load_config(*, namespace: str, env: str | None = None) -> dict:
    """Load the config dict for ``namespace`` in ``env``.

    ``env`` defaults from ``LIVEDATA_ENV``; pass an empty string for
    environment-independent files.
    """
    env = env if env is not None else os.getenv(ENV_VAR, DEFAULT_ENV).lower()
    suffix = f"_{env}" if env else ""
    config_file = f"{namespace}{suffix}.yaml"
    template_file = f"{namespace}{suffix}.yaml.jinja"
    root = resources.files("esslivedata_tpu.config.defaults")

    try:
        with root.joinpath(config_file).open() as f:
            return yaml.safe_load(f)
    except FileNotFoundError:
        pass
    try:
        with root.joinpath(template_file).open() as f:
            template_content = f.read()
    except FileNotFoundError:
        raise FileNotFoundError(
            f"Neither {config_file} nor {template_file} found in "
            "config defaults"
        ) from None
    from jinja2 import Template

    rendered = Template(template_content).render(
        **_template_env_vars(template_content)
    )
    return yaml.safe_load(rendered)

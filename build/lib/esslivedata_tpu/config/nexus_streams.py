"""Extract stream declarations from a NeXus file; generate registries.

ESS NeXus files double as the instrument's streaming manifest: any group
written by the file-writer carries ``topic``/``source``/``writer_module``
attributes naming the Kafka stream that fed it (reference:
nexus_helpers.py:68 walks the same convention). This module scans a file
for those declarations and renders the f144 subset into an importable
``streams_parsed.py`` registry module (ADR 0009: instruments ship O(100)
generated f144 declarations; hand-written specs only *name* and route
them).

Regenerate all instrument registries with::

    python scripts/generate_instrument_artifacts.py

or one file ad hoc::

    python -m esslivedata_tpu.config.nexus_streams geometry.nxs --out streams_parsed.py
"""

from __future__ import annotations

import io
from dataclasses import dataclass
from pathlib import Path

__all__ = [
    "StreamDecl",
    "scan_stream_groups",
    "render_registry_module",
    "generate_registry",
]


@dataclass(frozen=True)
class StreamDecl:
    """One stream-declaration group found in a NeXus file."""

    nexus_path: str
    topic: str
    source: str
    writer_module: str
    units: str | None = None
    nx_class: str = ""


def _attr_str(attrs, name: str) -> str | None:
    v = attrs.get(name)
    if v is None:
        return None
    if isinstance(v, bytes):
        return v.decode()
    return str(v)


def scan_stream_groups(path) -> list[StreamDecl]:
    """All groups carrying both ``topic`` and ``source`` attributes.

    Accepts a filesystem path or an open ``h5py.File``/``Group``.
    """
    import h5py

    decls: list[StreamDecl] = []

    def visit(name: str, node) -> None:
        if not isinstance(node, h5py.Group):
            return
        topic = _attr_str(node.attrs, "topic")
        source = _attr_str(node.attrs, "source")
        if topic is None or source is None:
            return
        decls.append(
            StreamDecl(
                nexus_path="/" + name.lstrip("/"),
                topic=topic,
                source=source,
                writer_module=_attr_str(node.attrs, "writer_module") or "",
                units=_attr_str(node.attrs, "units"),
                nx_class=_attr_str(node.attrs, "NX_class") or "",
            )
        )

    if isinstance(path, (str, Path)):
        with h5py.File(path, "r") as f:
            f.visititems(visit)
    else:
        path.visititems(visit)
    decls.sort(key=lambda d: d.nexus_path)
    return decls


def render_registry_module(
    decls: list[StreamDecl],
    *,
    source_file: str | None = None,
    writer_modules: tuple[str, ...] = ("f144",),
) -> str:
    """Render the registry module source for the selected writer modules."""
    out = io.StringIO()
    out.write('"""Generated f144 stream registry — do not edit.\n\n')
    out.write(
        "Regenerate: python scripts/generate_instrument_artifacts.py\n"
    )
    if source_file:
        out.write(f"Source artifact: {source_file}\n")
    out.write('"""\n\n')
    out.write("from esslivedata_tpu.config.stream import F144Stream\n\n")
    # Compact row form, expanded by a comprehension: one line per stream
    # keeps multi-hundred-entry registries reviewable in diffs.
    out.write("# (nexus_path, source, topic, units)\n")
    out.write("_ROWS: tuple[tuple[str, str, str, str | None], ...] = (\n")
    for d in decls:
        if d.writer_module not in writer_modules:
            continue
        out.write(
            f"    ({d.nexus_path!r}, {d.source!r}, {d.topic!r}, {d.units!r}),\n"
        )
    out.write(")\n\n")
    out.write(
        "PARSED_STREAMS: dict[str, F144Stream] = {\n"
        "    path: F144Stream(nexus_path=path, source=source, topic=topic, "
        "units=units)\n"
        "    for path, source, topic, units in _ROWS\n"
        "}\n"
    )
    return out.getvalue()


def generate_registry(
    nexus_path, out_path, *, source_file: str | None = None
) -> int:
    """Scan ``nexus_path`` and write the registry module to ``out_path``.
    Returns the number of f144 streams emitted."""
    decls = [
        d for d in scan_stream_groups(nexus_path) if d.writer_module == "f144"
    ]
    text = render_registry_module(decls, source_file=source_file)
    Path(out_path).write_text(text)
    return len(decls)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("nexus_file")
    parser.add_argument("--out", required=True)
    args = parser.parse_args(argv)
    n = generate_registry(
        args.nexus_file, args.out, source_file=Path(args.nexus_file).name
    )
    print(f"{args.out}: {n} f144 streams")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

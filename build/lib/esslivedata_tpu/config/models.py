"""Shared pydantic parameter models (reference: config/models.py, 500 LoC).

ROI shapes, axis ranges and common workflow parameters. These models ride
the commands topic as JSON and drive the dashboard's auto-generated forms.
"""

from __future__ import annotations

import numpy as np
from pydantic import BaseModel, ConfigDict, model_validator

__all__ = ["PolygonROI", "RectangleROI", "ROI", "TOARange", "WeightingMethod"]


class RectangleROI(BaseModel):
    """Axis-aligned rectangle in screen coordinates (bin units are the
    screen's coordinate units, not indices)."""

    model_config = ConfigDict(frozen=True)

    x_min: float
    x_max: float
    y_min: float
    y_max: float

    @model_validator(mode="after")
    def _ordered(self) -> RectangleROI:
        if self.x_max <= self.x_min or self.y_max <= self.y_min:
            raise ValueError("ROI bounds must satisfy min < max")
        return self

    def mask(self, x_centers: np.ndarray, y_centers: np.ndarray) -> np.ndarray:
        """Boolean [ny, nx] mask of screen bins inside the rectangle."""
        in_x = (x_centers >= self.x_min) & (x_centers <= self.x_max)
        in_y = (y_centers >= self.y_min) & (y_centers <= self.y_max)
        return in_y[:, None] & in_x[None, :]


class PolygonROI(BaseModel):
    """Closed polygon in screen coordinates."""

    model_config = ConfigDict(frozen=True)

    x: tuple[float, ...]
    y: tuple[float, ...]

    @model_validator(mode="after")
    def _enough_points(self) -> PolygonROI:
        if len(self.x) != len(self.y) or len(self.x) < 3:
            raise ValueError("Polygon needs >= 3 (x, y) points")
        return self

    def mask(self, x_centers: np.ndarray, y_centers: np.ndarray) -> np.ndarray:
        """Boolean [ny, nx] mask via even-odd ray casting (vectorized)."""
        xs = np.asarray(self.x)
        ys = np.asarray(self.y)
        gx, gy = np.meshgrid(x_centers, y_centers)  # [ny, nx]
        inside = np.zeros(gx.shape, dtype=bool)
        n = len(xs)
        for i in range(n):
            x0, y0 = xs[i], ys[i]
            x1, y1 = xs[(i + 1) % n], ys[(i + 1) % n]
            crosses = (y0 > gy) != (y1 > gy)
            with np.errstate(divide="ignore", invalid="ignore"):
                x_at = x0 + (gy - y0) * (x1 - x0) / (y1 - y0)
            inside ^= crosses & (gx < x_at)
        return inside


ROI = RectangleROI | PolygonROI


from ..core.constants import PULSE_PERIOD_NS_DEN, PULSE_PERIOD_NS_NUM

PULSE_PERIOD_NS = PULSE_PERIOD_NS_NUM / PULSE_PERIOD_NS_DEN
"""Full ESS frame in ns (derived from the canonical constants) — the
default TOA axis must cover the whole pulse or tail events silently vanish
from histograms."""


class TOARange(BaseModel):
    """Optional time-of-arrival filter window (ns within pulse)."""

    model_config = ConfigDict(frozen=True)

    enabled: bool = True
    low: float = 0.0
    high: float = PULSE_PERIOD_NS

    @model_validator(mode="after")
    def _ordered(self) -> TOARange:
        if self.high <= self.low:
            raise ValueError("TOA range must satisfy low < high")
        return self


class WeightingMethod(BaseModel):
    """Pixel-weighting toggle (reference: detector_view providers.py:98 —
    compensates solid-angle/projection density)."""

    model_config = ConfigDict(frozen=True)

    enabled: bool = False

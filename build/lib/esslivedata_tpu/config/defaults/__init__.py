"""Default infrastructure configs (reference: config/defaults/)."""

"""Derive a service's Kafka subscription from the specs it hosts.

Parity with reference ``config/route_derivation.py`` (gather_source_names:14,
resolve_stream_names:66, scope_stream_mapping:109): a backend service
subscribes only to the streams its hosted workflow specs actually consume —
source names, aux sources, spec/instrument context bindings — with Device
references expanded to their substreams (devices are synthesised in-process;
the subscription needs the substream topics) and logical detector/monitor
names (BIFROST's merged ``unified_detector``) expanded to every physical
stream of that category.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING

from ..workflows.workflow_factory import workflow_registry

logger = logging.getLogger(__name__)

if TYPE_CHECKING:
    from ..kafka.stream_mapping import StreamMapping
    from .instrument import Instrument
    from .workflow_spec import WorkflowSpec

__all__ = [
    "gather_source_names",
    "resolve_stream_names",
    "scope_stream_mapping",
    "spec_service",
]

#: Namespace prefix -> hosting service, for specs that don't set ``service``.
_NAMESPACE_SERVICE: tuple[tuple[str, str], ...] = (
    ("detector", "detector_data"),
    ("monitor", "monitor_data"),
    ("timeseries", "timeseries"),
    ("diagnostics", "timeseries"),
)


def spec_service(spec: WorkflowSpec) -> str:
    """The backend service hosting a spec (explicit field or namespace)."""
    if spec.service:
        return spec.service
    for prefix, service in _NAMESPACE_SERVICE:
        if spec.namespace.startswith(prefix):
            return service
    return "data_reduction"


def gather_source_names(instrument: Instrument, service: str) -> set[str]:
    """Stream names the specs hosted by ``service`` depend on.

    Device references expand into their substream names. Instrument-level
    context bindings apply when any hosted spec shares a source with the
    binding's dependent_sources (or the binding is unscoped).
    """
    specs = [
        s
        for s in workflow_registry.specs_for_instrument(instrument.name)
        if spec_service(s) == service
    ]
    names: set[str] = set()
    for spec in specs:
        names.update(spec.source_names)
        for choices in spec.aux_source_names.values():
            names.update(choices)
        names.update(spec.context_keys)
        # Optional context is routed like gating context — the service
        # must consume the stream to deliver it — the difference is
        # purely that jobs do not hold for it.
        names.update(spec.optional_context_keys)
    for binding in instrument.context_bindings:
        if not binding.dependent_sources or any(
            set(spec.source_names) & binding.dependent_sources for spec in specs
        ):
            names.add(binding.stream_name)
    if service == "timeseries":
        # Synthesis inputs owned by the timeseries service: the chopper
        # synthesizer consumes each chopper's speed-setpoint and delay
        # readback PVs; the device synthesizer consumes every device's
        # substreams. These are inputs of the synthesis layer, not of any
        # one spec, so they are added here rather than via spec sources.
        from .chopper import delay_readback_stream, speed_setpoint_stream

        for chopper in instrument.choppers:
            names.add(speed_setpoint_stream(chopper))
            names.add(delay_readback_stream(chopper))
        for device in instrument.devices.values():
            names.update(device.substream_names)
    devices = instrument.devices
    for name in list(names):
        if (device := devices.get(name)) is not None:
            names.discard(name)
            names.update(device.substream_names)
    return names


def resolve_stream_names(
    needed: set[str],
    instrument: Instrument,
    stream_mapping: StreamMapping,
) -> set[str]:
    """Expand logical source names to the physical names in the LUTs.

    A logical name absent from every LUT (BIFROST's merged detector) pulls
    in all physical names of its category. Synthesised streams (cascade
    trigger, delay setpoints, Device merges) have no LUT entry and simply
    drop out — they never ride Kafka.
    """
    known = stream_mapping.all_stream_names
    resolved = needed & known
    unknown = needed - known
    if not unknown:
        return resolved
    from ..kafka.stream_mapping import MERGED_DETECTOR_STREAM

    if unknown & set(instrument.detector_names) or (
        instrument.merge_detectors and MERGED_DETECTOR_STREAM in unknown
    ):
        # merge_detectors: specs address the single merged logical stream,
        # which appears in no LUT — the subscription needs every physical
        # bank. Other unknown names (synthesised streams) still drop out.
        resolved |= set(stream_mapping.detectors.values())
    if unknown & set(instrument.monitor_names):
        resolved |= set(stream_mapping.monitors.values())

    # Anything still unexplained is neither a LUT entry, a logical
    # detector/monitor name, nor a declared synthesised stream: almost
    # certainly a typo'd source_name in a spec, whose job would otherwise
    # wait for data forever with no diagnostic.
    from .chopper import CHOPPER_CASCADE_SOURCE, delay_setpoint_stream

    synthesized = {CHOPPER_CASCADE_SOURCE}
    synthesized.update(
        delay_setpoint_stream(chopper) for chopper in instrument.choppers
    )
    synthesized.update(instrument.devices)
    unexplained = (
        unknown
        - set(instrument.detector_names)
        - set(instrument.monitor_names)
        - {MERGED_DETECTOR_STREAM}
        - synthesized
    )
    if unexplained:
        logger.warning(
            "Source names %s for instrument %s match no stream LUT entry, "
            "logical detector/monitor name, or synthesised stream; jobs "
            "referencing them will never receive data (typo in a spec?)",
            sorted(unexplained),
            instrument.name,
        )
    return resolved


def scope_stream_mapping(
    instrument: Instrument,
    stream_mapping: StreamMapping,
    service: str,
) -> StreamMapping:
    """gather + resolve + filter in one call (the service-builder entry)."""
    needed = gather_source_names(instrument, service)
    needed = resolve_stream_names(needed, instrument, stream_mapping)
    return stream_mapping.filtered(needed)

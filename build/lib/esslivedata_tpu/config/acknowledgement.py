"""Command acknowledgement wire model (reference: config/acknowledgement.py).

Published as JSON on the responses topic; the dashboard's pending-command
tracker correlates by (source_name, job_number).
"""

from __future__ import annotations

import uuid
from typing import Literal

from pydantic import BaseModel

__all__ = ["CommandAcknowledgement"]


class CommandAcknowledgement(BaseModel):
    source_name: str
    job_number: uuid.UUID
    status: Literal["ack", "error"]
    message: str = ""
    service: str = ""

"""Streaming environment selector (reference: config/env.py).

DEV prefixes topics (``dev_<instrument>_*``) so a development broker can
coexist with production; PROD uses the facility topic names directly. The
active environment defaults from the ``LIVEDATA_ENV`` variable.
"""

from __future__ import annotations

import os
from enum import Enum

__all__ = ["DEFAULT_ENV", "ENV_VAR", "StreamingEnv", "current_env"]

ENV_VAR = "LIVEDATA_ENV"
DEFAULT_ENV = "dev"


class StreamingEnv(Enum):
    DEV = "dev"
    PROD = "prod"


def current_env() -> StreamingEnv:
    value = os.getenv(ENV_VAR, DEFAULT_ENV).lower()
    try:
        return StreamingEnv(value)
    except ValueError as err:
        raise ValueError(
            f"{ENV_VAR}={value!r} is not a valid environment; "
            f"expected one of {[e.value for e in StreamingEnv]}"
        ) from err

"""ODIN instrument declaration + spec registration.

Parity with reference ``config/instruments/odin/specs.py``: the Timepix3
event-mode imaging detector with an XY fold view (reference odin/views.py
fold_image), TOA-only monitors, plus an ad00 camera stream feeding the
area-detector workflow.
"""

from __future__ import annotations

import numpy as np

from ....config.instrument import (
    CameraConfig,
    DetectorConfig,
    Instrument,
    MonitorConfig,
    instrument_registry,
)
from ....config.workflow_spec import OutputSpec, WorkflowSpec
from ....workflows.area_detector_view import AreaDetectorParams
from ....workflows.detector_view.workflow import DetectorViewParams
from ....workflows.workflow_factory import workflow_registry
from .._common import (
    register_parsed_catalog,
    detector_view_outputs,
    register_monitor_spec,
    register_timeseries_spec,
)

TIMEPIX_SHAPE = (512, 512)

from .streams_parsed import PARSED_STREAMS

INSTRUMENT = Instrument(
    name="odin",
    _factories_module="esslivedata_tpu.config.instruments.odin.factories",
)
_n = TIMEPIX_SHAPE[0] * TIMEPIX_SHAPE[1]
INSTRUMENT.add_detector(
    DetectorConfig(
        name="timepix3",
        source_name="odin_timepix3",
        detector_number=np.arange(1, _n + 1, dtype=np.int32).reshape(
            TIMEPIX_SHAPE
        ),
        projection="logical",
    )
)
INSTRUMENT.add_monitor(MonitorConfig(name="monitor1", source_name="odin_mon_1"))
INSTRUMENT.add_monitor(MonitorConfig(name="monitor2", source_name="odin_mon_2"))
INSTRUMENT.add_camera(
    CameraConfig(name="orca_camera", source_name="odin_orca")
)
register_parsed_catalog(INSTRUMENT, PARSED_STREAMS)
instrument_registry.register(INSTRUMENT)

DETECTOR_XY_HANDLE = workflow_registry.register_spec(
    WorkflowSpec(
        instrument="odin",
        namespace="detector_view",
        name="odin_detector_xy",
        title="Timepix3 XY Detector Counts",
        description="2D view of the Timepix3 detector counts",
        source_names=["timepix3"],
        params_model=DetectorViewParams,
        outputs=detector_view_outputs(),
    )
)

CAMERA_HANDLE = workflow_registry.register_spec(
    WorkflowSpec(
        instrument="odin",
        namespace="detector_view",
        name="camera_view",
        title="Camera image",
        source_names=["orca_camera"],
        params_model=AreaDetectorParams,
        outputs={
            "current": OutputSpec(title="Frame (window)"),
            "cumulative": OutputSpec(
                title="Integrated image", view="since_start"
            ),
        },
    )
)

MONITOR_HANDLE = register_monitor_spec(INSTRUMENT)
TIMESERIES_HANDLE = register_timeseries_spec(INSTRUMENT)

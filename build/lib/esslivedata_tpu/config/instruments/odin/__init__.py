"""ODIN: neutron imaging with a Timepix3 event detector and an ad00 camera
(reference: config/instruments/odin)."""

from . import specs  # noqa: F401
from .specs import INSTRUMENT

__all__ = ["INSTRUMENT"]

"""ODIN factories."""

from __future__ import annotations

from functools import lru_cache

from ....workflows.area_detector_view import AreaDetectorView
from ....workflows.detector_view.projectors import (
    ProjectionTable,
    project_logical,
)
from ....workflows.detector_view.workflow import DetectorViewWorkflow
from ....workflows.monitor_workflow import MonitorWorkflow
from ....workflows.timeseries import TimeseriesWorkflow
from .specs import (
    CAMERA_HANDLE,
    DETECTOR_XY_HANDLE,
    INSTRUMENT,
    MONITOR_HANDLE,
    TIMESERIES_HANDLE,
)


@lru_cache(maxsize=None)
def _projection() -> ProjectionTable:
    return project_logical(INSTRUMENT.detectors["timepix3"].detector_number)


@DETECTOR_XY_HANDLE.attach_factory
def make_detector_xy(*, source_name: str, params) -> DetectorViewWorkflow:  # noqa: ARG001
    return DetectorViewWorkflow(projection=_projection(), params=params)


@CAMERA_HANDLE.attach_factory
def make_camera_view(*, source_name: str, params) -> AreaDetectorView:  # noqa: ARG001
    return AreaDetectorView(params=params)


@MONITOR_HANDLE.attach_factory
def make_monitor(*, source_name: str, params) -> MonitorWorkflow:  # noqa: ARG001
    return MonitorWorkflow(params=params)


@TIMESERIES_HANDLE.attach_factory
def make_timeseries(*, source_name: str, params) -> TimeseriesWorkflow:  # noqa: ARG001
    return TimeseriesWorkflow()

"""BIFROST (indirect-geometry spectrometer): 9 analyzer-triplet banks with
a merged detector stream and mesh-shardable multi-bank reduction
(reference: config/instruments/bifrost; BASELINE config 3)."""

from . import specs  # noqa: F401
from .specs import INSTRUMENT

__all__ = ["INSTRUMENT"]

"""Generated f144 stream registry — do not edit.

Regenerate: python scripts/generate_instrument_artifacts.py
Source artifact: geometry-bifrost-<date>.nxs (synthesized)
"""

from esslivedata_tpu.config.stream import F144Stream

# (nexus_path, source, topic, units)
_ROWS: tuple[tuple[str, str, str, str | None], ...] = (
    ('/entry/analyzer_env/temperature_1', 'BIFR-Ana:Tmp-TIC-001', 'bifrost_sample_env', 'K'),
    ('/entry/analyzer_env/temperature_2', 'BIFR-Ana:Tmp-TIC-002', 'bifrost_sample_env', 'K'),
    ('/entry/analyzer_env/temperature_3', 'BIFR-Ana:Tmp-TIC-003', 'bifrost_sample_env', 'K'),
    ('/entry/analyzer_env/temperature_4', 'BIFR-Ana:Tmp-TIC-004', 'bifrost_sample_env', 'K'),
    ('/entry/analyzer_env/temperature_5', 'BIFR-Ana:Tmp-TIC-005', 'bifrost_sample_env', 'K'),
    ('/entry/analyzer_env/temperature_6', 'BIFR-Ana:Tmp-TIC-006', 'bifrost_sample_env', 'K'),
    ('/entry/analyzer_env/temperature_7', 'BIFR-Ana:Tmp-TIC-007', 'bifrost_sample_env', 'K'),
    ('/entry/analyzer_env/temperature_8', 'BIFR-Ana:Tmp-TIC-008', 'bifrost_sample_env', 'K'),
    ('/entry/analyzer_env/temperature_9', 'BIFR-Ana:Tmp-TIC-009', 'bifrost_sample_env', 'K'),
    ('/entry/instrument/analyzer_1/goniometer/idle_flag', 'BIFR-Ana1:MC-RotX-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/analyzer_1/goniometer/target_value', 'BIFR-Ana1:MC-RotX-01:Mtr.VAL', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_1/goniometer/value', 'BIFR-Ana1:MC-RotX-01:Mtr.RBV', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_2/goniometer/idle_flag', 'BIFR-Ana2:MC-RotX-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/analyzer_2/goniometer/target_value', 'BIFR-Ana2:MC-RotX-01:Mtr.VAL', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_2/goniometer/value', 'BIFR-Ana2:MC-RotX-01:Mtr.RBV', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_3/goniometer/idle_flag', 'BIFR-Ana3:MC-RotX-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/analyzer_3/goniometer/target_value', 'BIFR-Ana3:MC-RotX-01:Mtr.VAL', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_3/goniometer/value', 'BIFR-Ana3:MC-RotX-01:Mtr.RBV', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_4/goniometer/idle_flag', 'BIFR-Ana4:MC-RotX-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/analyzer_4/goniometer/target_value', 'BIFR-Ana4:MC-RotX-01:Mtr.VAL', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_4/goniometer/value', 'BIFR-Ana4:MC-RotX-01:Mtr.RBV', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_5/goniometer/idle_flag', 'BIFR-Ana5:MC-RotX-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/analyzer_5/goniometer/target_value', 'BIFR-Ana5:MC-RotX-01:Mtr.VAL', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_5/goniometer/value', 'BIFR-Ana5:MC-RotX-01:Mtr.RBV', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_6/goniometer/idle_flag', 'BIFR-Ana6:MC-RotX-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/analyzer_6/goniometer/target_value', 'BIFR-Ana6:MC-RotX-01:Mtr.VAL', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_6/goniometer/value', 'BIFR-Ana6:MC-RotX-01:Mtr.RBV', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_7/goniometer/idle_flag', 'BIFR-Ana7:MC-RotX-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/analyzer_7/goniometer/target_value', 'BIFR-Ana7:MC-RotX-01:Mtr.VAL', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_7/goniometer/value', 'BIFR-Ana7:MC-RotX-01:Mtr.RBV', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_8/goniometer/idle_flag', 'BIFR-Ana8:MC-RotX-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/analyzer_8/goniometer/target_value', 'BIFR-Ana8:MC-RotX-01:Mtr.VAL', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_8/goniometer/value', 'BIFR-Ana8:MC-RotX-01:Mtr.RBV', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_9/goniometer/idle_flag', 'BIFR-Ana9:MC-RotX-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/analyzer_9/goniometer/target_value', 'BIFR-Ana9:MC-RotX-01:Mtr.VAL', 'bifrost_motion', 'deg'),
    ('/entry/instrument/analyzer_9/goniometer/value', 'BIFR-Ana9:MC-RotX-01:Mtr.RBV', 'bifrost_motion', 'deg'),
    ('/entry/instrument/frame_overlap_chopper/delay', 'BIFR-Chop:FOC-01:Delay', 'bifrost_choppers', 'ns'),
    ('/entry/instrument/frame_overlap_chopper/phase', 'BIFR-Chop:FOC-01:Phs', 'bifrost_choppers', 'deg'),
    ('/entry/instrument/frame_overlap_chopper/rotation_speed', 'BIFR-Chop:FOC-01:Spd', 'bifrost_choppers', 'Hz'),
    ('/entry/instrument/frame_overlap_chopper/rotation_speed_setpoint', 'BIFR-Chop:FOC-01:SpdSet', 'bifrost_choppers', 'Hz'),
    ('/entry/instrument/pulse_shaping_chopper/delay', 'BIFR-Chop:PSC-01:Delay', 'bifrost_choppers', 'ns'),
    ('/entry/instrument/pulse_shaping_chopper/phase', 'BIFR-Chop:PSC-01:Phs', 'bifrost_choppers', 'deg'),
    ('/entry/instrument/pulse_shaping_chopper/rotation_speed', 'BIFR-Chop:PSC-01:Spd', 'bifrost_choppers', 'Hz'),
    ('/entry/instrument/pulse_shaping_chopper/rotation_speed_setpoint', 'BIFR-Chop:PSC-01:SpdSet', 'bifrost_choppers', 'Hz'),
    ('/entry/instrument/sample_stage/omega/idle_flag', 'BIFR-Smpl:MC-RotZ-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/omega/target_value', 'BIFR-Smpl:MC-RotZ-01:Mtr.VAL', 'bifrost_motion', 'deg'),
    ('/entry/instrument/sample_stage/omega/value', 'BIFR-Smpl:MC-RotZ-01:Mtr.RBV', 'bifrost_motion', 'deg'),
    ('/entry/instrument/sample_stage/x/idle_flag', 'BIFR-Smpl:MC-LinX-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/x/target_value', 'BIFR-Smpl:MC-LinX-01:Mtr.VAL', 'bifrost_motion', 'mm'),
    ('/entry/instrument/sample_stage/x/value', 'BIFR-Smpl:MC-LinX-01:Mtr.RBV', 'bifrost_motion', 'mm'),
    ('/entry/instrument/sample_stage/y/idle_flag', 'BIFR-Smpl:MC-LinY-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/y/target_value', 'BIFR-Smpl:MC-LinY-01:Mtr.VAL', 'bifrost_motion', 'mm'),
    ('/entry/instrument/sample_stage/y/value', 'BIFR-Smpl:MC-LinY-01:Mtr.RBV', 'bifrost_motion', 'mm'),
    ('/entry/instrument/sample_stage/z/idle_flag', 'BIFR-Smpl:MC-LinZ-01:Mtr.DMOV', 'bifrost_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/z/target_value', 'BIFR-Smpl:MC-LinZ-01:Mtr.VAL', 'bifrost_motion', 'mm'),
    ('/entry/instrument/sample_stage/z/value', 'BIFR-Smpl:MC-LinZ-01:Mtr.RBV', 'bifrost_motion', 'mm'),
    ('/entry/sample/magnetic_field', 'BIFROST-SE:Mag-PSU-101', 'bifrost_sample_env', 'T'),
    ('/entry/sample/pressure', 'BIFROST-SE:Prs-PIC-101', 'bifrost_sample_env', 'bar'),
    ('/entry/sample/temperature_1', 'BIFROST-SE:Tmp-TIC-101', 'bifrost_sample_env', 'K'),
    ('/entry/sample/temperature_2', 'BIFROST-SE:Tmp-TIC-102', 'bifrost_sample_env', 'K'),
)

PARSED_STREAMS: dict[str, F144Stream] = {
    path: F144Stream(nexus_path=path, source=source, topic=topic, units=units)
    for path, source, topic, units in _ROWS
}

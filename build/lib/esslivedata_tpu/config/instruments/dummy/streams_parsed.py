"""Generated f144 stream registry — do not edit.

Regenerate: python scripts/generate_instrument_artifacts.py
Source artifact: geometry-dummy-<date>.nxs (synthesized)
"""

from esslivedata_tpu.config.stream import F144Stream

# (nexus_path, source, topic, units)
_ROWS: tuple[tuple[str, str, str, str | None], ...] = (
    ('/entry/instrument/sample_changer/position/idle_flag', 'DMY-MC:SmplPos.DMOV', 'dummy_motion', 'dimensionless'),
    ('/entry/instrument/sample_changer/position/target_value', 'DMY-MC:SmplPos.VAL', 'dummy_motion', 'mm'),
    ('/entry/instrument/sample_changer/position/value', 'DMY-MC:SmplPos.RBV', 'dummy_motion', 'mm'),
    ('/entry/sample/magnetic_field', 'DUMMY-SE:Mag-PSU-101', 'dummy_sample_env', 'T'),
    ('/entry/sample/pressure', 'DUMMY-SE:Prs-PIC-101', 'dummy_sample_env', 'bar'),
    ('/entry/sample/temperature_1', 'DUMMY-SE:Tmp-TIC-101', 'dummy_sample_env', 'K'),
)

PARSED_STREAMS: dict[str, F144Stream] = {
    path: F144Stream(nexus_path=path, source=source, topic=topic, units=units)
    for path, source, topic, units in _ROWS
}

"""Dummy instrument: small logical-panel detector for tests, demos and
benchmark config 1 (reference: config/instruments/dummy)."""

from . import specs  # noqa: F401  (registers instrument + specs on import)
from .specs import INSTRUMENT

__all__ = ["INSTRUMENT"]

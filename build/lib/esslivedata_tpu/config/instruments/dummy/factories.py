"""Dummy instrument factories (heavy imports; loaded lazily by
``Instrument.load_factories``, reference: instruments/*/factories.py)."""

from __future__ import annotations

from functools import lru_cache

from ....workflows.detector_view.projectors import ProjectionTable, project_logical
from ....workflows.detector_view.workflow import DetectorViewWorkflow
from ....workflows.monitor_workflow import MonitorWorkflow
from ....workflows.timeseries import TimeseriesWorkflow
from .specs import (
    DETECTOR_VIEW_HANDLE,
    INSTRUMENT,
    MONITOR_HANDLE,
    TIMESERIES_HANDLE,
)


@lru_cache(maxsize=None)
def _projection_for(detector_name: str) -> ProjectionTable:
    det = INSTRUMENT.detectors[detector_name]
    return project_logical(det.detector_number)


@DETECTOR_VIEW_HANDLE.attach_factory
def make_detector_view(*, source_name: str, params) -> DetectorViewWorkflow:
    return DetectorViewWorkflow(
        projection=_projection_for(source_name), params=params
    )


@MONITOR_HANDLE.attach_factory
def make_monitor(*, source_name: str, params) -> MonitorWorkflow:
    return MonitorWorkflow(params=params)


@TIMESERIES_HANDLE.attach_factory
def make_timeseries(*, source_name: str, params) -> TimeseriesWorkflow:
    return TimeseriesWorkflow()

"""DREAM instrument declaration + spec registration.

Parity with reference ``config/instruments/dream/specs.py``: five voxel
detector banks, bunker/cave monitors, five choppers (pulse-shaping pair,
band, overlap, T0) feeding the wavelength-LUT workflow, and the three
mantle logical views (front-layer, wire, strip; reference dream/views.py)
expressed as N-d projection LUTs. Voxel layouts follow the published DREAM
module structure; exact per-bank NeXus geometry plugs in when artifacts
are available (same caveat as loki/specs.py).
"""

from __future__ import annotations

import numpy as np

from ....config.instrument import (
    DetectorConfig,
    Instrument,
    MonitorConfig,
    instrument_registry,
)
from ....config.chopper import chopper_pv_streams
from ....config.workflow_spec import OutputSpec, WorkflowSpec
from ....workflows.detector_view.projectors import NdLogicalView
from ....workflows.detector_view.workflow import DetectorViewParams
from ....workflows.wavelength_lut_workflow import (
    ChopperGeometry,
    WavelengthLutParams,
    spec_context_keys,
)
from ....workflows.powder import PowderDiffractionParams
from ....workflows.workflow_factory import workflow_registry
from .._common import (
    register_parsed_catalog,
    detector_view_outputs,
    register_monitor_spec,
    register_timeseries_spec,
)

#: Voxel layout per bank (dim name -> size), C-order of detector_number.
BANK_SIZES: dict[str, dict[str, int]] = {
    "mantle_detector": {
        "wire": 32,
        "module": 5,
        "segment": 6,
        "strip": 256,
        "counter": 2,
    },
    "endcap_backward_detector": {
        "strip": 16,
        "wire": 16,
        "module": 11,
        "segment": 28,
        "counter": 2,
    },
    "endcap_forward_detector": {
        "strip": 16,
        "wire": 16,
        "module": 5,
        "segment": 28,
        "counter": 2,
    },
    "high_resolution_detector": {
        "strip": 32,
        "wire": 16,
        "module": 3,
        "segment": 20,
        "counter": 2,
    },
    "sans_detector": {
        "strip": 32,
        "wire": 16,
        "module": 3,
        "segment": 10,
        "counter": 2,
    },
}

#: The three mantle views of reference dream/views.py, as LUT specs.
MANTLE_VIEWS: dict[str, NdLogicalView] = {
    "mantle_front_layer": NdLogicalView(
        sizes=BANK_SIZES["mantle_detector"],
        select={"wire": 0},
        y=("module", "segment", "counter"),
        x=("strip",),
    ),
    "mantle_wire_view": NdLogicalView(
        sizes=BANK_SIZES["mantle_detector"],
        y=("wire",),
        x=("module", "segment", "counter"),
        # 'strip' omitted -> summed by the scatter.
    ),
    "mantle_strip_view": NdLogicalView(
        sizes=BANK_SIZES["mantle_detector"],
        y=("strip",),
        # everything else summed.
    ),
}

CHOPPERS = [
    "pulse_shaping_chopper1",
    "pulse_shaping_chopper2",
    "band_chopper",
    "overlap_chopper",
    "T0_chopper",
]

#: Static chopper geometry (distances from moderator; slit spans chosen to
#: approximate the high-flux configuration).
CHOPPER_GEOMETRY = [
    ChopperGeometry(
        name="pulse_shaping_chopper1",
        distance_m=6.145,
        slit_edges_deg=((0.0, 72.0), (180.0, 252.0)),
    ),
    ChopperGeometry(
        name="pulse_shaping_chopper2",
        distance_m=6.155,
        slit_edges_deg=((0.0, 72.0), (180.0, 252.0)),
    ),
    ChopperGeometry(
        name="band_chopper", distance_m=9.3, slit_edges_deg=((0.0, 130.0),)
    ),
    ChopperGeometry(
        name="overlap_chopper", distance_m=13.5, slit_edges_deg=((0.0, 150.0),)
    ),
    ChopperGeometry(
        name="T0_chopper", distance_m=8.5, slit_edges_deg=((20.0, 340.0),)
    ),
]


from .streams_parsed import PARSED_STREAMS

INSTRUMENT = Instrument(
    name="dream",
    streams=chopper_pv_streams(CHOPPERS, topic="dream_choppers"),
    choppers=CHOPPERS,
    _factories_module="esslivedata_tpu.config.instruments.dream.factories",
)

# Bank layouts come from the date-resolved NeXus geometry artifact; the
# declared axis sizes must agree with the file or the spec fails at import
# (a mismatched geometry file is a deployment error, not a runtime one).
from ...geometry_store import geometry_path, load_logical_layout  # noqa: E402

_geometry = geometry_path("dream")
for _bank, _sizes in BANK_SIZES.items():
    _layout = load_logical_layout(_geometry, _bank)
    if _layout.shape != tuple(_sizes.values()):
        raise ValueError(
            f"DREAM bank {_bank}: geometry file layout {_layout.shape} != "
            f"declared axis sizes {tuple(_sizes.values())}"
        )
    INSTRUMENT.add_detector(
        DetectorConfig(
            name=_bank,
            source_name=f"dream_{_bank}",
            detector_number=_layout,
            projection="logical",
        )
    )

INSTRUMENT.add_monitor(
    MonitorConfig(name="monitor_bunker", source_name="dream_mon_bunker")
)
INSTRUMENT.add_monitor(
    MonitorConfig(name="monitor_cave", source_name="dream_mon_cave")
)
INSTRUMENT.add_log("sample_temperature", "dream_temp_sample")
# WFM subframe emission-time calibration (ns), published by the chopper
# control layer; the powder workflow consumes it as OPTIONAL context.
INSTRUMENT.add_log("emission_offset", "dream_wfm_t0")
register_parsed_catalog(INSTRUMENT, PARSED_STREAMS)
instrument_registry.register(INSTRUMENT)


#: One detector-view spec per mantle view, plus a generic per-bank view.
MANTLE_VIEW_HANDLES = {
    view_name: workflow_registry.register_spec(
        WorkflowSpec(
            instrument="dream",
            namespace="detector_view",
            name=view_name,
            title=view_name.replace("_", " ").title(),
            source_names=["mantle_detector"],
            params_model=DetectorViewParams,
            outputs=detector_view_outputs(),
        )
    )
    for view_name in MANTLE_VIEWS
}

BANK_VIEW_HANDLE = workflow_registry.register_spec(
    WorkflowSpec(
        instrument="dream",
        namespace="detector_view",
        name="bank_view",
        title="Bank strip/position view",
        source_names=sorted(set(BANK_SIZES) - {"mantle_detector"}),
        params_model=DetectorViewParams,
        outputs=detector_view_outputs(),
    )
)

MONITOR_HANDLE = register_monitor_spec(INSTRUMENT)

WAVELENGTH_LUT_HANDLE = workflow_registry.register_spec(
    WorkflowSpec(
        instrument="dream",
        namespace="diagnostics",
        name="wavelength_lut",
        title="TOF->wavelength lookup table",
        source_names=["chopper_cascade"],
        params_model=WavelengthLutParams,
        context_keys=spec_context_keys(CHOPPER_GEOMETRY),
        reset_on_run_transition=False,
        outputs={
            "wavelength_lut": OutputSpec(title="Wavelength LUT"),
            "wavelength_bands": OutputSpec(title="Wavelength bands"),
        },
    )
)

TIMESERIES_HANDLE = register_timeseries_spec(INSTRUMENT)


def powder_geometry(bank: str) -> dict[str, np.ndarray]:
    """Synthetic per-pixel diffraction geometry for one bank.

    Placeholder in the spirit of the instrument (real deployments read
    pixel positions from the facility geometry file): the mantle wraps
    scattering angles 32°-148° along its strip axis; endcap banks sit
    forward/backward of the sample. Flight path = 76.55 m moderator->
    sample plus a secondary path growing modestly across the bank.
    """
    layout = INSTRUMENT.detectors[bank].detector_number
    ids = layout.reshape(-1)
    n = ids.size
    # The scattering angle varies along the STRIP axis (the mantle's
    # cylinder axis direction); wire depth and module/segment position
    # leave it nearly unchanged. Use each pixel's strip coordinate, not
    # the flattened index (which walks the wire/depth axis first).
    sizes = BANK_SIZES[bank]
    shape = tuple(sizes.values())
    strip_axis = list(sizes).index("strip")
    strip_idx = np.unravel_index(np.arange(n), shape)[strip_axis]
    frac = strip_idx / max(shape[strip_axis] - 1, 1)
    if bank == "mantle_detector":
        two_theta = np.deg2rad(32.0 + 116.0 * frac)
    elif "backward" in bank:
        two_theta = np.deg2rad(130.0 + 40.0 * frac)
    else:
        two_theta = np.deg2rad(10.0 + 35.0 * frac)
    # Secondary flight path grows modestly with wire depth.
    wire_axis = list(sizes).index("wire")
    wire_idx = np.unravel_index(np.arange(n), shape)[wire_axis]
    l_total = 76.55 + 1.1 + 0.02 * wire_idx
    return {
        "two_theta": two_theta,
        "l_total": l_total,
        "pixel_ids": ids.astype(np.int64),
    }


def _powder_outputs() -> dict[str, OutputSpec]:
    return {
        "dspacing_current": OutputSpec(title="I(d) — window"),
        "dspacing_cumulative": OutputSpec(
            title="I(d) — since start", view="since_start"
        ),
        "dspacing_normalized": OutputSpec(
            title="I(d) / monitor", view="since_start"
        ),
        "dspacing_two_theta": OutputSpec(
            title="I(d, 2theta)", view="since_start"
        ),
        "focussed_tof": OutputSpec(
            title="Focussed spectrum (TOF axis)", view="since_start"
        ),
        "counts_current": OutputSpec(title="Events binned"),
        "monitor_counts_current": OutputSpec(title="Monitor counts"),
    }


def _powder_spec(name: str, title: str, outputs: dict) -> WorkflowSpec:
    return WorkflowSpec(
        instrument="dream",
        namespace="powder",
        name=name,
        title=title,
        source_names=list(BANK_SIZES),
        service="data_reduction",
        aux_source_names={"monitor": ["monitor_bunker", "monitor_cave"]},
        # Delivered when the facility publishes it; never gated on — the
        # static toa_offset_ns param is the fallback.
        optional_context_keys=["emission_offset"],
        params_model=PowderDiffractionParams,
        outputs=outputs,
    )


POWDER_HANDLE = workflow_registry.register_spec(
    _powder_spec(
        "dspacing", "I(d) powder pattern (Bragg rebinning)", _powder_outputs()
    )
)


POWDER_VANADIUM_HANDLE = workflow_registry.register_spec(
    _powder_spec(
        "dspacing_vanadium",
        "I(d) with vanadium normalization",
        {
            **_powder_outputs(),
            "intensity_dspacing": OutputSpec(
                title="I(d) vanadium-corrected", view="since_start"
            ),
        },
    )
)

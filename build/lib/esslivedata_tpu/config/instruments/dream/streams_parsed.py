"""Generated f144 stream registry — do not edit.

Regenerate: python scripts/generate_instrument_artifacts.py
Source artifact: geometry-dream-<date>.nxs (synthesized)
"""

from esslivedata_tpu.config.stream import F144Stream

# (nexus_path, source, topic, units)
_ROWS: tuple[tuple[str, str, str, str | None], ...] = (
    ('/entry/instrument/T0_chopper/delay', 'T0_chopper:Delay', 'dream_choppers', 'ns'),
    ('/entry/instrument/T0_chopper/phase', 'T0_chopper:Phs', 'dream_choppers', 'deg'),
    ('/entry/instrument/T0_chopper/rotation_speed', 'T0_chopper:Spd', 'dream_choppers', 'Hz'),
    ('/entry/instrument/T0_chopper/rotation_speed_setpoint', 'T0_chopper:SpdSet', 'dream_choppers', 'Hz'),
    ('/entry/instrument/band_chopper/delay', 'band_chopper:Delay', 'dream_choppers', 'ns'),
    ('/entry/instrument/band_chopper/phase', 'band_chopper:Phs', 'dream_choppers', 'deg'),
    ('/entry/instrument/band_chopper/rotation_speed', 'band_chopper:Spd', 'dream_choppers', 'Hz'),
    ('/entry/instrument/band_chopper/rotation_speed_setpoint', 'band_chopper:SpdSet', 'dream_choppers', 'Hz'),
    ('/entry/instrument/collimator/rotation/idle_flag', 'DREAM-Coll:MC-RotZ-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/collimator/rotation/target_value', 'DREAM-Coll:MC-RotZ-01:Mtr.VAL', 'dream_motion', 'deg'),
    ('/entry/instrument/collimator/rotation/value', 'DREAM-Coll:MC-RotZ-01:Mtr.RBV', 'dream_motion', 'deg'),
    ('/entry/instrument/collimator/z/idle_flag', 'DREAM-Coll:MC-LinZ-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/collimator/z/target_value', 'DREAM-Coll:MC-LinZ-01:Mtr.VAL', 'dream_motion', 'mm'),
    ('/entry/instrument/collimator/z/value', 'DREAM-Coll:MC-LinZ-01:Mtr.RBV', 'dream_motion', 'mm'),
    ('/entry/instrument/divergence_slit/x_center/idle_flag', 'DREAM-DivSl:MC-SlCenX-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/divergence_slit/x_center/target_value', 'DREAM-DivSl:MC-SlCenX-01:Mtr.VAL', 'dream_motion', 'mm'),
    ('/entry/instrument/divergence_slit/x_center/value', 'DREAM-DivSl:MC-SlCenX-01:Mtr.RBV', 'dream_motion', 'mm'),
    ('/entry/instrument/divergence_slit/x_gap/idle_flag', 'DREAM-DivSl:MC-SlGapX-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/divergence_slit/x_gap/target_value', 'DREAM-DivSl:MC-SlGapX-01:Mtr.VAL', 'dream_motion', 'mm'),
    ('/entry/instrument/divergence_slit/x_gap/value', 'DREAM-DivSl:MC-SlGapX-01:Mtr.RBV', 'dream_motion', 'mm'),
    ('/entry/instrument/divergence_slit/y_center/idle_flag', 'DREAM-DivSl:MC-SlCenY-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/divergence_slit/y_center/target_value', 'DREAM-DivSl:MC-SlCenY-01:Mtr.VAL', 'dream_motion', 'mm'),
    ('/entry/instrument/divergence_slit/y_center/value', 'DREAM-DivSl:MC-SlCenY-01:Mtr.RBV', 'dream_motion', 'mm'),
    ('/entry/instrument/divergence_slit/y_gap/idle_flag', 'DREAM-DivSl:MC-SlGapY-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/divergence_slit/y_gap/target_value', 'DREAM-DivSl:MC-SlGapY-01:Mtr.VAL', 'dream_motion', 'mm'),
    ('/entry/instrument/divergence_slit/y_gap/value', 'DREAM-DivSl:MC-SlGapY-01:Mtr.RBV', 'dream_motion', 'mm'),
    ('/entry/instrument/monitor_cave/monitor_positioner/idle_flag', 'DREAM-MonC:MC-LinZ-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/monitor_cave/monitor_positioner/target_value', 'DREAM-MonC:MC-LinZ-01:Mtr.VAL', 'dream_motion', 'mm'),
    ('/entry/instrument/monitor_cave/monitor_positioner/value', 'DREAM-MonC:MC-LinZ-01:Mtr.RBV', 'dream_motion', 'mm'),
    ('/entry/instrument/overlap_chopper/delay', 'overlap_chopper:Delay', 'dream_choppers', 'ns'),
    ('/entry/instrument/overlap_chopper/phase', 'overlap_chopper:Phs', 'dream_choppers', 'deg'),
    ('/entry/instrument/overlap_chopper/rotation_speed', 'overlap_chopper:Spd', 'dream_choppers', 'Hz'),
    ('/entry/instrument/overlap_chopper/rotation_speed_setpoint', 'overlap_chopper:SpdSet', 'dream_choppers', 'Hz'),
    ('/entry/instrument/polarizer/state/idle_flag', 'DREAM-Pol:MC-LinX-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/polarizer/state/target_value', 'DREAM-Pol:MC-LinX-01:Mtr.VAL', 'dream_motion', 'mm'),
    ('/entry/instrument/polarizer/state/value', 'DREAM-Pol:MC-LinX-01:Mtr.RBV', 'dream_motion', 'mm'),
    ('/entry/instrument/pulse_shaping_chopper1/delay', 'pulse_shaping_chopper1:Delay', 'dream_choppers', 'ns'),
    ('/entry/instrument/pulse_shaping_chopper1/phase', 'pulse_shaping_chopper1:Phs', 'dream_choppers', 'deg'),
    ('/entry/instrument/pulse_shaping_chopper1/rotation_speed', 'pulse_shaping_chopper1:Spd', 'dream_choppers', 'Hz'),
    ('/entry/instrument/pulse_shaping_chopper1/rotation_speed_setpoint', 'pulse_shaping_chopper1:SpdSet', 'dream_choppers', 'Hz'),
    ('/entry/instrument/pulse_shaping_chopper2/delay', 'pulse_shaping_chopper2:Delay', 'dream_choppers', 'ns'),
    ('/entry/instrument/pulse_shaping_chopper2/phase', 'pulse_shaping_chopper2:Phs', 'dream_choppers', 'deg'),
    ('/entry/instrument/pulse_shaping_chopper2/rotation_speed', 'pulse_shaping_chopper2:Spd', 'dream_choppers', 'Hz'),
    ('/entry/instrument/pulse_shaping_chopper2/rotation_speed_setpoint', 'pulse_shaping_chopper2:SpdSet', 'dream_choppers', 'Hz'),
    ('/entry/instrument/sample_stage/omega/idle_flag', 'DREAM-Smpl:MC-RotZ-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/omega/target_value', 'DREAM-Smpl:MC-RotZ-01:Mtr.VAL', 'dream_motion', 'deg'),
    ('/entry/instrument/sample_stage/omega/value', 'DREAM-Smpl:MC-RotZ-01:Mtr.RBV', 'dream_motion', 'deg'),
    ('/entry/instrument/sample_stage/x/idle_flag', 'DREAM-Smpl:MC-LinX-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/x/target_value', 'DREAM-Smpl:MC-LinX-01:Mtr.VAL', 'dream_motion', 'mm'),
    ('/entry/instrument/sample_stage/x/value', 'DREAM-Smpl:MC-LinX-01:Mtr.RBV', 'dream_motion', 'mm'),
    ('/entry/instrument/sample_stage/y/idle_flag', 'DREAM-Smpl:MC-LinY-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/y/target_value', 'DREAM-Smpl:MC-LinY-01:Mtr.VAL', 'dream_motion', 'mm'),
    ('/entry/instrument/sample_stage/y/value', 'DREAM-Smpl:MC-LinY-01:Mtr.RBV', 'dream_motion', 'mm'),
    ('/entry/instrument/sample_stage/z/idle_flag', 'DREAM-Smpl:MC-LinZ-01:Mtr.DMOV', 'dream_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/z/target_value', 'DREAM-Smpl:MC-LinZ-01:Mtr.VAL', 'dream_motion', 'mm'),
    ('/entry/instrument/sample_stage/z/value', 'DREAM-Smpl:MC-LinZ-01:Mtr.RBV', 'dream_motion', 'mm'),
    ('/entry/sample/magnetic_field', 'DREAM-SE:Mag-PSU-101', 'dream_sample_env', 'T'),
    ('/entry/sample/pressure', 'DREAM-SE:Prs-PIC-101', 'dream_sample_env', 'bar'),
    ('/entry/sample/temperature_1', 'DREAM-SE:Tmp-TIC-101', 'dream_sample_env', 'K'),
    ('/entry/sample/temperature_2', 'DREAM-SE:Tmp-TIC-102', 'dream_sample_env', 'K'),
    ('/entry/sample/temperature_3', 'DREAM-SE:Tmp-TIC-103', 'dream_sample_env', 'K'),
    ('/entry/vacuum/gauge_1', 'DREAM-Vac:VGP-001', 'dream_vacuum', 'mbar'),
    ('/entry/vacuum/gauge_2', 'DREAM-Vac:VGP-002', 'dream_vacuum', 'mbar'),
    ('/entry/vacuum/gauge_3', 'DREAM-Vac:VGP-003', 'dream_vacuum', 'mbar'),
)

PARSED_STREAMS: dict[str, F144Stream] = {
    path: F144Stream(nexus_path=path, source=source, topic=topic, units=units)
    for path, source, topic, units in _ROWS
}

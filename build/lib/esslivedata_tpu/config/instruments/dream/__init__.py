"""DREAM: powder diffractometer with voxel (wire/module/segment/strip/
counter) detectors and N-d logical views (reference:
config/instruments/dream)."""

from . import specs  # noqa: F401  (registers instrument + specs on import)
from .specs import INSTRUMENT

__all__ = ["INSTRUMENT"]

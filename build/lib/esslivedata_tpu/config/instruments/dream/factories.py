"""DREAM factories: N-d view projection tables + kernels, built lazily."""

from __future__ import annotations

from functools import lru_cache

from ....workflows.detector_view.projectors import (
    NdLogicalView,
    ProjectionTable,
    project_logical_nd,
)
from ....workflows.detector_view.workflow import DetectorViewWorkflow
from ....workflows.monitor_workflow import MonitorWorkflow
from ....workflows.powder import (
    PowderDiffractionWorkflow,
    PowderVanadiumWorkflow,
)
from ....workflows.timeseries import TimeseriesWorkflow
from ....workflows.wavelength_lut_workflow import WavelengthLutWorkflow
from .._common import monitor_streams_from_aux
from .specs import (
    BANK_SIZES,
    POWDER_HANDLE,
    POWDER_VANADIUM_HANDLE,
    BANK_VIEW_HANDLE,
    CHOPPER_GEOMETRY,
    INSTRUMENT,
    MANTLE_VIEW_HANDLES,
    MANTLE_VIEWS,
    MONITOR_HANDLE,
    TIMESERIES_HANDLE,
    WAVELENGTH_LUT_HANDLE,
    powder_geometry,
)


@lru_cache(maxsize=None)
def _mantle_projection(view_name: str) -> ProjectionTable:
    det = INSTRUMENT.detectors["mantle_detector"]
    return project_logical_nd(det.detector_number, MANTLE_VIEWS[view_name])


@lru_cache(maxsize=None)
def _bank_projection(bank: str) -> ProjectionTable:
    """Generic strip-vs-rest view for the non-mantle banks."""
    sizes = BANK_SIZES[bank]
    others = tuple(d for d in sizes if d != "strip")
    view = NdLogicalView(sizes=sizes, y=("strip",), x=others)
    return project_logical_nd(
        INSTRUMENT.detectors[bank].detector_number, view
    )


for _view_name, _handle in MANTLE_VIEW_HANDLES.items():

    def _make_mantle(*, source_name: str, params, _v=_view_name):  # noqa: ARG001
        return DetectorViewWorkflow(
            projection=_mantle_projection(_v), params=params
        )

    _handle.attach_factory(_make_mantle)


@BANK_VIEW_HANDLE.attach_factory
def make_bank_view(*, source_name: str, params) -> DetectorViewWorkflow:
    return DetectorViewWorkflow(
        projection=_bank_projection(source_name), params=params
    )


@MONITOR_HANDLE.attach_factory
def make_monitor(*, source_name: str, params) -> MonitorWorkflow:  # noqa: ARG001
    return MonitorWorkflow(params=params)


@WAVELENGTH_LUT_HANDLE.attach_factory
def make_wavelength_lut(*, source_name: str, params) -> WavelengthLutWorkflow:  # noqa: ARG001
    return WavelengthLutWorkflow(choppers=CHOPPER_GEOMETRY, params=params)


@TIMESERIES_HANDLE.attach_factory
def make_timeseries(*, source_name: str, params) -> TimeseriesWorkflow:  # noqa: ARG001
    return TimeseriesWorkflow()


def _make_powder_factory(workflow_cls):
    def factory(*, source_name: str, params, aux_source_names=None):
        return workflow_cls(
            **powder_geometry(source_name),
            params=params,
            primary_stream=source_name,
            monitor_streams=monitor_streams_from_aux(aux_source_names),
        )

    return factory


make_powder = POWDER_HANDLE.attach_factory(
    _make_powder_factory(PowderDiffractionWorkflow)
)
make_powder_vanadium = POWDER_VANADIUM_HANDLE.attach_factory(
    _make_powder_factory(PowderVanadiumWorkflow)
)

"""LOKI (SANS) instrument: geometric detector banks + monitor-normalized
I(Q) reduction (reference: config/instruments/loki; BASELINE configs 2+4)."""

from . import specs  # noqa: F401
from .specs import INSTRUMENT

__all__ = ["INSTRUMENT"]

"""LOKI factories: projection tables + kernels built lazily."""

from __future__ import annotations

from functools import lru_cache

from ....workflows.detector_view.projectors import ProjectionTable, project_geometric
from ....workflows.detector_view.workflow import DetectorViewWorkflow
from ....workflows.monitor_workflow import MonitorWorkflow
from ....workflows.sans import SansIQWorkflow
from ....workflows.wavelength_spectrum import WavelengthSpectrumWorkflow
from ....workflows.timeseries import TimeseriesWorkflow
from .specs import (
    DETECTOR_VIEW_HANDLE,
    INSTRUMENT,
    MONITOR_HANDLE,
    SANS_IQ_HANDLE,
    TIMESERIES_HANDLE,
    WAVELENGTH_SPECTRUM_HANDLE,
)


@lru_cache(maxsize=None)
def _projection_for(detector_name: str) -> ProjectionTable:
    det = INSTRUMENT.detectors[detector_name]
    return project_geometric(
        det.positions,
        det.pixel_ids,
        mode=det.projection,
        resolution=det.resolution,
        noise_sigma=det.noise_sigma,
        n_replica=det.n_replica,
    )


@DETECTOR_VIEW_HANDLE.attach_factory
def make_detector_view(*, source_name: str, params) -> DetectorViewWorkflow:
    return DetectorViewWorkflow(
        projection=_projection_for(source_name), params=params
    )


@MONITOR_HANDLE.attach_factory
def make_monitor(*, source_name: str, params) -> MonitorWorkflow:
    return MonitorWorkflow(params=params)


@SANS_IQ_HANDLE.attach_factory
def make_sans_iq(*, source_name: str, params, aux_source_names=None) -> SansIQWorkflow:
    det = INSTRUMENT.detectors[source_name]
    aux = aux_source_names or {}
    # Transmission only runs when the aux slot is bound: with no binding
    # there is no second monitor to ratio against, fraction stays 1.
    transmission = (
        {aux["transmission_monitor"]} if "transmission_monitor" in aux else None
    )
    # An unbound incident slot falls back to all monitors MINUS the
    # transmission stream — counting it on both channels would inflate
    # the incident denominator and skew T.
    monitors = (
        {aux["monitor"]}
        if "monitor" in aux
        else set(INSTRUMENT.monitor_names) - (transmission or set())
    )
    if transmission and monitors & transmission:
        # Same stream on both channels would make T identically 1 —
        # vacuous but plausible-looking; refuse instead.
        raise ValueError(
            "incident and transmission monitor must be different streams; "
            f"both bound to {sorted(monitors & transmission)}"
        )
    return SansIQWorkflow(
        positions=det.positions,
        pixel_ids=det.pixel_ids,
        params=params,
        primary_stream=source_name,
        monitor_streams=monitors,
        transmission_streams=transmission,
    )


@TIMESERIES_HANDLE.attach_factory
def make_timeseries(*, source_name: str, params) -> TimeseriesWorkflow:
    return TimeseriesWorkflow()


@WAVELENGTH_SPECTRUM_HANDLE.attach_factory
def make_wavelength_spectrum(
    *, source_name: str, params, aux_source_names=None
) -> WavelengthSpectrumWorkflow:
    det = INSTRUMENT.detectors[source_name]
    aux = aux_source_names or {}
    monitors = (
        {aux["monitor"]} if "monitor" in aux else set(INSTRUMENT.monitor_names)
    )
    return WavelengthSpectrumWorkflow(
        positions=det.positions,
        pixel_ids=det.pixel_ids,
        params=params,
        primary_stream=source_name,
        monitor_streams=monitors,
    )

"""LOKI rear-bank geometry, loaded from the NeXus geometry artifact.

The positions and pixel ids come from the date-resolved geometry file
(``config/geometry_store.py`` — reference parity:
preprocessors/detector_data.py:66-127, where real deployments fetch the
artifact with pooch and ``LIVEDATA_DATA_DIR`` overrides the cache). The
synthesized artifact carries a 256x256 pixel plane, 1 m x 1 m, 5 m
downstream of the sample — the right scale and topology for the
detector-view and I(Q) paths; a real ESS file dropped into the cache is
picked up with no code change.
"""

from __future__ import annotations

import numpy as np

NY, NX = 256, 256


def rear_bank_geometry() -> tuple[np.ndarray, np.ndarray]:
    """Returns ([n, 3] positions in m, [n] pixel ids starting at 1)."""
    from ...geometry_store import geometry_path, load_detector_geometry

    path = geometry_path("loki")
    positions, pixel_ids = load_detector_geometry(path, "larmor_detector")
    if pixel_ids.size != NY * NX:
        raise ValueError(
            f"LOKI geometry file {path} has {pixel_ids.size} pixels; the "
            f"declared rear-bank layout expects {NY}x{NX}"
        )
    return positions, pixel_ids

"""Built-in instrument packages (reference: config/instruments/{dummy,loki,
dream,bifrost,...}). Each package registers its Instrument + workflow specs
at import; heavy factories attach via ``Instrument.load_factories()``.
"""

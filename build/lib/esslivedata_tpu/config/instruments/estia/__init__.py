"""ESTIA: reflectometer with a multiblade detector (reference:
config/instruments/estia)."""

from . import specs  # noqa: F401
from .specs import INSTRUMENT

__all__ = ["INSTRUMENT"]

"""Generated f144 stream registry — do not edit.

Regenerate: python scripts/generate_instrument_artifacts.py
Source artifact: geometry-estia-<date>.nxs (synthesized)
"""

from esslivedata_tpu.config.stream import F144Stream

# (nexus_path, source, topic, units)
_ROWS: tuple[tuple[str, str, str, str | None], ...] = (
    ('/entry/instrument/chopper_1/delay', 'ESTIA-Chop:C1:Delay', 'estia_choppers', 'ns'),
    ('/entry/instrument/chopper_1/phase', 'ESTIA-Chop:C1:Phs', 'estia_choppers', 'deg'),
    ('/entry/instrument/chopper_1/rotation_speed', 'ESTIA-Chop:C1:Spd', 'estia_choppers', 'Hz'),
    ('/entry/instrument/chopper_1/rotation_speed_setpoint', 'ESTIA-Chop:C1:SpdSet', 'estia_choppers', 'Hz'),
    ('/entry/instrument/chopper_2/delay', 'ESTIA-Chop:C2:Delay', 'estia_choppers', 'ns'),
    ('/entry/instrument/chopper_2/phase', 'ESTIA-Chop:C2:Phs', 'estia_choppers', 'deg'),
    ('/entry/instrument/chopper_2/rotation_speed', 'ESTIA-Chop:C2:Spd', 'estia_choppers', 'Hz'),
    ('/entry/instrument/chopper_2/rotation_speed_setpoint', 'ESTIA-Chop:C2:SpdSet', 'estia_choppers', 'Hz'),
    ('/entry/instrument/detector_arm/two_theta/idle_flag', 'ESTIA-DetArm:MC-RotZ-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/detector_arm/two_theta/target_value', 'ESTIA-DetArm:MC-RotZ-01:Mtr.VAL', 'estia_motion', 'deg'),
    ('/entry/instrument/detector_arm/two_theta/value', 'ESTIA-DetArm:MC-RotZ-01:Mtr.RBV', 'estia_motion', 'deg'),
    ('/entry/instrument/sample_stage/chi/idle_flag', 'ESTIA-Smpl:MC-RotX-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/chi/target_value', 'ESTIA-Smpl:MC-RotX-01:Mtr.VAL', 'estia_motion', 'deg'),
    ('/entry/instrument/sample_stage/chi/value', 'ESTIA-Smpl:MC-RotX-01:Mtr.RBV', 'estia_motion', 'deg'),
    ('/entry/instrument/sample_stage/omega/idle_flag', 'ESTIA-Smpl:MC-RotZ-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/omega/target_value', 'ESTIA-Smpl:MC-RotZ-01:Mtr.VAL', 'estia_motion', 'deg'),
    ('/entry/instrument/sample_stage/omega/value', 'ESTIA-Smpl:MC-RotZ-01:Mtr.RBV', 'estia_motion', 'deg'),
    ('/entry/instrument/sample_stage/x/idle_flag', 'ESTIA-Smpl:MC-LinX-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/x/target_value', 'ESTIA-Smpl:MC-LinX-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/sample_stage/x/value', 'ESTIA-Smpl:MC-LinX-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/instrument/sample_stage/y/idle_flag', 'ESTIA-Smpl:MC-LinY-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/y/target_value', 'ESTIA-Smpl:MC-LinY-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/sample_stage/y/value', 'ESTIA-Smpl:MC-LinY-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/instrument/sample_stage/z/idle_flag', 'ESTIA-Smpl:MC-LinZ-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/z/target_value', 'ESTIA-Smpl:MC-LinZ-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/sample_stage/z/value', 'ESTIA-Smpl:MC-LinZ-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_1/x_center/idle_flag', 'ESTIA-Sl1:MC-SlCenX-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/slit_1/x_center/target_value', 'ESTIA-Sl1:MC-SlCenX-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_1/x_center/value', 'ESTIA-Sl1:MC-SlCenX-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_1/x_gap/idle_flag', 'ESTIA-Sl1:MC-SlGapX-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/slit_1/x_gap/target_value', 'ESTIA-Sl1:MC-SlGapX-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_1/x_gap/value', 'ESTIA-Sl1:MC-SlGapX-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_1/y_center/idle_flag', 'ESTIA-Sl1:MC-SlCenY-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/slit_1/y_center/target_value', 'ESTIA-Sl1:MC-SlCenY-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_1/y_center/value', 'ESTIA-Sl1:MC-SlCenY-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_1/y_gap/idle_flag', 'ESTIA-Sl1:MC-SlGapY-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/slit_1/y_gap/target_value', 'ESTIA-Sl1:MC-SlGapY-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_1/y_gap/value', 'ESTIA-Sl1:MC-SlGapY-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_2/x_center/idle_flag', 'ESTIA-Sl2:MC-SlCenX-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/slit_2/x_center/target_value', 'ESTIA-Sl2:MC-SlCenX-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_2/x_center/value', 'ESTIA-Sl2:MC-SlCenX-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_2/x_gap/idle_flag', 'ESTIA-Sl2:MC-SlGapX-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/slit_2/x_gap/target_value', 'ESTIA-Sl2:MC-SlGapX-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_2/x_gap/value', 'ESTIA-Sl2:MC-SlGapX-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_2/y_center/idle_flag', 'ESTIA-Sl2:MC-SlCenY-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/slit_2/y_center/target_value', 'ESTIA-Sl2:MC-SlCenY-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_2/y_center/value', 'ESTIA-Sl2:MC-SlCenY-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_2/y_gap/idle_flag', 'ESTIA-Sl2:MC-SlGapY-01:Mtr.DMOV', 'estia_motion', 'dimensionless'),
    ('/entry/instrument/slit_2/y_gap/target_value', 'ESTIA-Sl2:MC-SlGapY-01:Mtr.VAL', 'estia_motion', 'mm'),
    ('/entry/instrument/slit_2/y_gap/value', 'ESTIA-Sl2:MC-SlGapY-01:Mtr.RBV', 'estia_motion', 'mm'),
    ('/entry/sample/magnetic_field', 'ESTIA-SE:Mag-PSU-101', 'estia_sample_env', 'T'),
    ('/entry/sample/pressure', 'ESTIA-SE:Prs-PIC-101', 'estia_sample_env', 'bar'),
    ('/entry/sample/temperature_1', 'ESTIA-SE:Tmp-TIC-101', 'estia_sample_env', 'K'),
    ('/entry/sample/temperature_2', 'ESTIA-SE:Tmp-TIC-102', 'estia_sample_env', 'K'),
)

PARSED_STREAMS: dict[str, F144Stream] = {
    path: F144Stream(nexus_path=path, source=source, topic=topic, units=units)
    for path, source, topic, units in _ROWS
}

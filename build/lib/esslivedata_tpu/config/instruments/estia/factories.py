"""ESTIA factories."""

from __future__ import annotations

from functools import lru_cache

from ....workflows.detector_view.projectors import (
    ProjectionTable,
    project_logical,
    project_logical_nd,
)
from ....workflows.detector_view.workflow import DetectorViewWorkflow
from ....workflows.monitor_workflow import MonitorWorkflow
from ....workflows.reflectometry import ReflectometryWorkflow
from ....workflows.timeseries import TimeseriesWorkflow
from .._common import monitor_streams_from_aux
from .specs import (
    INSTRUMENT,
    MONITOR_HANDLE,
    PIXEL_MONITOR_VIEW_HANDLE,
    REFLECTOMETRY_HANDLE,
    TIMESERIES_HANDLE,
    VIEW_HANDLES,
    VIEWS,
    reflectometry_geometry,
)


@lru_cache(maxsize=None)
def _projection(view_name: str) -> ProjectionTable:
    det = INSTRUMENT.detectors["multiblade_detector"]
    return project_logical_nd(det.detector_number, VIEWS[view_name])


for _view_name, _handle in VIEW_HANDLES.items():

    def _make_view(*, source_name: str, params, _v=_view_name):  # noqa: ARG001
        return DetectorViewWorkflow(projection=_projection(_v), params=params)

    _handle.attach_factory(_make_view)


@MONITOR_HANDLE.attach_factory
def make_monitor(*, source_name: str, params) -> MonitorWorkflow:  # noqa: ARG001
    return MonitorWorkflow(params=params)


@lru_cache(maxsize=None)
def _pixel_monitor_projection(name: str) -> ProjectionTable:
    # The pixellated monitor's [ny, nx] grid IS the screen layout.
    return project_logical(INSTRUMENT.monitors[name].detector_number)


@PIXEL_MONITOR_VIEW_HANDLE.attach_factory
def make_pixel_monitor_view(*, source_name: str, params) -> DetectorViewWorkflow:
    return DetectorViewWorkflow(
        projection=_pixel_monitor_projection(source_name), params=params
    )


@TIMESERIES_HANDLE.attach_factory
def make_timeseries(*, source_name: str, params) -> TimeseriesWorkflow:  # noqa: ARG001
    return TimeseriesWorkflow()


@REFLECTOMETRY_HANDLE.attach_factory
def make_reflectometry(
    *, source_name: str, params, aux_source_names=None
) -> ReflectometryWorkflow:
    return ReflectometryWorkflow(
        **reflectometry_geometry(),
        params=params,
        primary_stream=source_name,
        monitor_streams=monitor_streams_from_aux(aux_source_names),
    )

"""ESTIA instrument declaration + spec registration.

Parity with reference ``config/instruments/estia/specs.py``: the
multiblade reflectometry detector (blade x wire x strip voxels), the cbm1
beam monitor, and a blade-resolved detector view plus a specular
reflectivity-style projection (wire vs strip summed over blades).
"""

from __future__ import annotations

import numpy as np

from ....config.instrument import (
    DetectorConfig,
    Instrument,
    MonitorConfig,
    instrument_registry,
)
from ....config.workflow_spec import OutputSpec, WorkflowSpec
from ....workflows.detector_view.projectors import NdLogicalView
from ....workflows.detector_view.workflow import DetectorViewParams
from ....workflows.reflectometry import ReflectometryParams
from ....workflows.workflow_factory import workflow_registry
from .._common import (
    register_parsed_catalog,
    detector_view_outputs,
    register_monitor_spec,
    register_timeseries_spec,
)

#: Multiblade layout: 48 blades, 32 wires (depth), 64 strips (transverse).
BLADE_SIZES = {"blade": 48, "wire": 32, "strip": 64}

VIEWS: dict[str, NdLogicalView] = {
    # Blade-resolved: one row per (blade, wire), strips across.
    "blade_wire": NdLogicalView(
        sizes=BLADE_SIZES, y=("blade", "wire"), x=("strip",)
    ),
    # Specular view: wire (scattering angle proxy) vs strip, blades summed.
    "angle_strip": NdLogicalView(sizes=BLADE_SIZES, y=("wire",), x=("strip",)),
}

from .streams_parsed import PARSED_STREAMS

INSTRUMENT = Instrument(
    name="estia",
    _factories_module="esslivedata_tpu.config.instruments.estia.factories",
)
_n = int(np.prod(list(BLADE_SIZES.values())))
INSTRUMENT.add_detector(
    DetectorConfig(
        name="multiblade_detector",
        source_name="estia_multiblade",
        detector_number=np.arange(1, _n + 1, dtype=np.int32).reshape(
            tuple(BLADE_SIZES.values())
        ),
        projection="logical",
    )
)
INSTRUMENT.add_monitor(MonitorConfig(name="cbm1", source_name="estia_cbm1"))
# cbm1 is a pixellated beam monitor (a small camera-style grid with
# meaningful per-pixel event ids — reference instrument.py:401): pixel
# ids survive the adapter and feed the 2-D monitor view below.
PIXEL_MONITOR_SHAPE = (32, 32)
INSTRUMENT.configure_pixellated_monitor(
    "cbm1",
    detector_number=np.arange(
        1, PIXEL_MONITOR_SHAPE[0] * PIXEL_MONITOR_SHAPE[1] + 1, dtype=np.int32
    ).reshape(PIXEL_MONITOR_SHAPE),
)
INSTRUMENT.add_log("sample_angle", "estia_mtr_omega")
register_parsed_catalog(INSTRUMENT, PARSED_STREAMS)
instrument_registry.register(INSTRUMENT)

VIEW_HANDLES = {
    view_name: workflow_registry.register_spec(
        WorkflowSpec(
            instrument="estia",
            namespace="detector_view",
            name=view_name,
            title=view_name.replace("_", " ").title(),
            source_names=["multiblade_detector"],
            params_model=DetectorViewParams,
            outputs=detector_view_outputs(),
        )
    )
    for view_name in VIEWS
}

MONITOR_HANDLE = register_monitor_spec(INSTRUMENT)
TIMESERIES_HANDLE = register_timeseries_spec(INSTRUMENT)

#: 2-D view over the pixellated beam monitor: same detector-view engine,
#: projected through the monitor's logical pixel grid.
PIXEL_MONITOR_VIEW_HANDLE = workflow_registry.register_spec(
    WorkflowSpec(
        instrument="estia",
        namespace="monitor_data",
        name="pixel_view",
        title="Beam monitor image",
        source_names=INSTRUMENT.pixellated_monitor_names,
        params_model=DetectorViewParams,
        outputs=detector_view_outputs(),
    )
)


def reflectometry_geometry() -> dict[str, np.ndarray]:
    """Synthetic per-pixel reflectometry geometry (placeholder pending
    the facility geometry file): each multiblade wire sits a small angle
    above the horizon (the Selene guide's ~1.5 deg span across the 32
    wires, identical for every blade and strip), with the secondary
    flight path ~4 m growing slightly with wire depth."""
    shape = tuple(BLADE_SIZES.values())
    n = int(np.prod(shape))
    wire_axis = list(BLADE_SIZES).index("wire")
    wire_idx = np.unravel_index(np.arange(n), shape)[wire_axis]
    wire_frac = wire_idx / (BLADE_SIZES["wire"] - 1)
    pixel_offset_rad = np.deg2rad(0.1 + 1.5 * wire_frac)
    l2 = 4.0 + 0.05 * wire_idx / BLADE_SIZES["wire"]
    ids = INSTRUMENT.detectors["multiblade_detector"].detector_number.reshape(-1)
    return {
        "pixel_offset_rad": pixel_offset_rad,
        "l2": l2,
        "pixel_ids": ids.astype(np.int64),
    }


REFLECTOMETRY_HANDLE = workflow_registry.register_spec(
    WorkflowSpec(
        instrument="estia",
        namespace="reflectometry",
        name="r_qz",
        title="R(Qz) specular reflectivity",
        source_names=["multiblade_detector"],
        service="data_reduction",
        aux_source_names={"monitor": ["cbm1"]},
        # Gate on the live sample rotation: R(Qz) is undefined until the
        # angle is known, and the Qz table rebuilds when it moves.
        context_keys=["sample_angle"],
        params_model=ReflectometryParams,
        outputs={
            "r_qz_current": OutputSpec(title="R(Qz) — window"),
            "r_qz_cumulative": OutputSpec(
                title="R(Qz) — since start", view="since_start"
            ),
            "r_qz_normalized": OutputSpec(
                title="R(Qz) / monitor", view="since_start"
            ),
            "counts_current": OutputSpec(title="Events binned"),
            "monitor_counts_current": OutputSpec(title="Monitor counts"),
            "sample_angle_deg": OutputSpec(title="Sample angle in use"),
        },
    )
)

"""NMX instrument declaration + spec registration.

Parity with reference ``config/instruments/nmx/specs.py``: three
1280x1280-pixel detector panels with a panel_xy view (TOA-only monitors —
NMX registers no TOF lookup tables).
"""

from __future__ import annotations

import numpy as np

from ....config.instrument import (
    DetectorConfig,
    Instrument,
    MonitorConfig,
    instrument_registry,
)
from ....config.workflow_spec import WorkflowSpec
from ....workflows.detector_view.workflow import DetectorViewParams
from ....workflows.workflow_factory import workflow_registry
from .._common import (
    register_parsed_catalog,
    detector_view_outputs,
    register_monitor_spec,
    register_timeseries_spec,
)

PANEL_SHAPE = (1280, 1280)
PANELS = ["detector_panel_0", "detector_panel_1", "detector_panel_2"]

from .streams_parsed import PARSED_STREAMS

INSTRUMENT = Instrument(
    name="nmx",
    _factories_module="esslivedata_tpu.config.instruments.nmx.factories",
)
_panel_pixels = PANEL_SHAPE[0] * PANEL_SHAPE[1]
for _i, _panel in enumerate(PANELS):
    _start = 1 + _i * _panel_pixels
    INSTRUMENT.add_detector(
        DetectorConfig(
            name=_panel,
            source_name=f"nmx_{_panel}",
            detector_number=np.arange(
                _start, _start + _panel_pixels, dtype=np.int32
            ).reshape(PANEL_SHAPE),
            projection="logical",
        )
    )
INSTRUMENT.add_monitor(MonitorConfig(name="monitor1", source_name="nmx_mon_1"))
INSTRUMENT.add_monitor(MonitorConfig(name="monitor2", source_name="nmx_mon_2"))
register_parsed_catalog(INSTRUMENT, PARSED_STREAMS)
instrument_registry.register(INSTRUMENT)

PANEL_XY_HANDLE = workflow_registry.register_spec(
    WorkflowSpec(
        instrument="nmx",
        namespace="detector_view",
        name="panel_xy",
        title="Detector counts",
        description="Detector counts per pixel.",
        source_names=PANELS,
        params_model=DetectorViewParams,
        outputs=detector_view_outputs(),
    )
)
MONITOR_HANDLE = register_monitor_spec(INSTRUMENT)
TIMESERIES_HANDLE = register_timeseries_spec(INSTRUMENT)

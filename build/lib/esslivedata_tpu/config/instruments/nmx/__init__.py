"""NMX: macromolecular crystallography with three large area panels
(reference: config/instruments/nmx)."""

from . import specs  # noqa: F401
from .specs import INSTRUMENT

__all__ = ["INSTRUMENT"]

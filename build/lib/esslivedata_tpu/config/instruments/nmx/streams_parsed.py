"""Generated f144 stream registry — do not edit.

Regenerate: python scripts/generate_instrument_artifacts.py
Source artifact: geometry-nmx-<date>.nxs (synthesized)
"""

from esslivedata_tpu.config.stream import F144Stream

# (nexus_path, source, topic, units)
_ROWS: tuple[tuple[str, str, str, str | None], ...] = (
    ('/entry/instrument/chopper_1/delay', 'NMX-Chop:C1:Delay', 'nmx_choppers', 'ns'),
    ('/entry/instrument/chopper_1/phase', 'NMX-Chop:C1:Phs', 'nmx_choppers', 'deg'),
    ('/entry/instrument/chopper_1/rotation_speed', 'NMX-Chop:C1:Spd', 'nmx_choppers', 'Hz'),
    ('/entry/instrument/chopper_1/rotation_speed_setpoint', 'NMX-Chop:C1:SpdSet', 'nmx_choppers', 'Hz'),
    ('/entry/instrument/detector_panel_0/distance/idle_flag', 'NMX-Det0:MC-LinZ-01:Mtr.DMOV', 'nmx_motion', 'dimensionless'),
    ('/entry/instrument/detector_panel_0/distance/target_value', 'NMX-Det0:MC-LinZ-01:Mtr.VAL', 'nmx_motion', 'm'),
    ('/entry/instrument/detector_panel_0/distance/value', 'NMX-Det0:MC-LinZ-01:Mtr.RBV', 'nmx_motion', 'm'),
    ('/entry/instrument/detector_panel_0/rotation/idle_flag', 'NMX-Det0:MC-RotZ-01:Mtr.DMOV', 'nmx_motion', 'dimensionless'),
    ('/entry/instrument/detector_panel_0/rotation/target_value', 'NMX-Det0:MC-RotZ-01:Mtr.VAL', 'nmx_motion', 'deg'),
    ('/entry/instrument/detector_panel_0/rotation/value', 'NMX-Det0:MC-RotZ-01:Mtr.RBV', 'nmx_motion', 'deg'),
    ('/entry/instrument/detector_panel_1/distance/idle_flag', 'NMX-Det1:MC-LinZ-01:Mtr.DMOV', 'nmx_motion', 'dimensionless'),
    ('/entry/instrument/detector_panel_1/distance/target_value', 'NMX-Det1:MC-LinZ-01:Mtr.VAL', 'nmx_motion', 'm'),
    ('/entry/instrument/detector_panel_1/distance/value', 'NMX-Det1:MC-LinZ-01:Mtr.RBV', 'nmx_motion', 'm'),
    ('/entry/instrument/detector_panel_1/rotation/idle_flag', 'NMX-Det1:MC-RotZ-01:Mtr.DMOV', 'nmx_motion', 'dimensionless'),
    ('/entry/instrument/detector_panel_1/rotation/target_value', 'NMX-Det1:MC-RotZ-01:Mtr.VAL', 'nmx_motion', 'deg'),
    ('/entry/instrument/detector_panel_1/rotation/value', 'NMX-Det1:MC-RotZ-01:Mtr.RBV', 'nmx_motion', 'deg'),
    ('/entry/instrument/detector_panel_2/distance/idle_flag', 'NMX-Det2:MC-LinZ-01:Mtr.DMOV', 'nmx_motion', 'dimensionless'),
    ('/entry/instrument/detector_panel_2/distance/target_value', 'NMX-Det2:MC-LinZ-01:Mtr.VAL', 'nmx_motion', 'm'),
    ('/entry/instrument/detector_panel_2/distance/value', 'NMX-Det2:MC-LinZ-01:Mtr.RBV', 'nmx_motion', 'm'),
    ('/entry/instrument/detector_panel_2/rotation/idle_flag', 'NMX-Det2:MC-RotZ-01:Mtr.DMOV', 'nmx_motion', 'dimensionless'),
    ('/entry/instrument/detector_panel_2/rotation/target_value', 'NMX-Det2:MC-RotZ-01:Mtr.VAL', 'nmx_motion', 'deg'),
    ('/entry/instrument/detector_panel_2/rotation/value', 'NMX-Det2:MC-RotZ-01:Mtr.RBV', 'nmx_motion', 'deg'),
    ('/entry/instrument/sample_stage/omega/idle_flag', 'NMX-Smpl:MC-RotZ-01:Mtr.DMOV', 'nmx_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/omega/target_value', 'NMX-Smpl:MC-RotZ-01:Mtr.VAL', 'nmx_motion', 'deg'),
    ('/entry/instrument/sample_stage/omega/value', 'NMX-Smpl:MC-RotZ-01:Mtr.RBV', 'nmx_motion', 'deg'),
    ('/entry/instrument/sample_stage/x/idle_flag', 'NMX-Smpl:MC-LinX-01:Mtr.DMOV', 'nmx_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/x/target_value', 'NMX-Smpl:MC-LinX-01:Mtr.VAL', 'nmx_motion', 'mm'),
    ('/entry/instrument/sample_stage/x/value', 'NMX-Smpl:MC-LinX-01:Mtr.RBV', 'nmx_motion', 'mm'),
    ('/entry/instrument/sample_stage/y/idle_flag', 'NMX-Smpl:MC-LinY-01:Mtr.DMOV', 'nmx_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/y/target_value', 'NMX-Smpl:MC-LinY-01:Mtr.VAL', 'nmx_motion', 'mm'),
    ('/entry/instrument/sample_stage/y/value', 'NMX-Smpl:MC-LinY-01:Mtr.RBV', 'nmx_motion', 'mm'),
    ('/entry/instrument/sample_stage/z/idle_flag', 'NMX-Smpl:MC-LinZ-01:Mtr.DMOV', 'nmx_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/z/target_value', 'NMX-Smpl:MC-LinZ-01:Mtr.VAL', 'nmx_motion', 'mm'),
    ('/entry/instrument/sample_stage/z/value', 'NMX-Smpl:MC-LinZ-01:Mtr.RBV', 'nmx_motion', 'mm'),
    ('/entry/sample/magnetic_field', 'NMX-SE:Mag-PSU-101', 'nmx_sample_env', 'T'),
    ('/entry/sample/pressure', 'NMX-SE:Prs-PIC-101', 'nmx_sample_env', 'bar'),
    ('/entry/sample/temperature_1', 'NMX-SE:Tmp-TIC-101', 'nmx_sample_env', 'K'),
    ('/entry/sample/temperature_2', 'NMX-SE:Tmp-TIC-102', 'nmx_sample_env', 'K'),
)

PARSED_STREAMS: dict[str, F144Stream] = {
    path: F144Stream(nexus_path=path, source=source, topic=topic, units=units)
    for path, source, topic, units in _ROWS
}

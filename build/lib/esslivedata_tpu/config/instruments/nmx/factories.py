"""NMX factories: per-panel identity projections (1280x1280 grids)."""

from __future__ import annotations

from functools import lru_cache

from ....workflows.detector_view.projectors import (
    ProjectionTable,
    project_logical,
)
from ....workflows.detector_view.workflow import DetectorViewWorkflow
from ....workflows.monitor_workflow import MonitorWorkflow
from ....workflows.timeseries import TimeseriesWorkflow
from .specs import INSTRUMENT, MONITOR_HANDLE, PANEL_XY_HANDLE, TIMESERIES_HANDLE


@lru_cache(maxsize=None)
def _projection(panel: str) -> ProjectionTable:
    return project_logical(INSTRUMENT.detectors[panel].detector_number)


@PANEL_XY_HANDLE.attach_factory
def make_panel_xy(*, source_name: str, params) -> DetectorViewWorkflow:
    return DetectorViewWorkflow(
        projection=_projection(source_name), params=params
    )


@MONITOR_HANDLE.attach_factory
def make_monitor(*, source_name: str, params) -> MonitorWorkflow:  # noqa: ARG001
    return MonitorWorkflow(params=params)


@TIMESERIES_HANDLE.attach_factory
def make_timeseries(*, source_name: str, params) -> TimeseriesWorkflow:  # noqa: ARG001
    return TimeseriesWorkflow()

"""TBL (test beamline) instrument declaration + spec registration.

Parity with reference ``config/instruments/tbl/specs.py``: the detector
ZOO the test beamline hosts — Timepix3, Multiblade (blade/wire/strip
fold, views.py:24), two He3 tube banks (tube/pixel axes, views.py:28),
nGEM, and the ORCA area camera (ad00) — plus a small 2-D panel, one
monitor, sample-environment logs, and a WFM chopper pair whose setpoints
feed the wavelength-LUT workflow.
"""

from __future__ import annotations

import numpy as np

from ....config.instrument import (
    CameraConfig,
    DetectorConfig,
    Instrument,
    MonitorConfig,
    instrument_registry,
)
from ....config.chopper import chopper_pv_streams
from ....config.workflow_spec import OutputSpec, WorkflowSpec
from ....workflows.area_detector_view import AreaDetectorParams
from ....workflows.detector_view.projectors import NdLogicalView
from ....workflows.detector_view.workflow import DetectorViewParams
from ....workflows.wavelength_lut_workflow import (
    ChopperGeometry,
    WavelengthLutParams,
    spec_context_keys,
)
from ....workflows.workflow_factory import workflow_registry
from .._common import (
    register_parsed_catalog,
    detector_view_outputs,
    register_monitor_spec,
    register_timeseries_spec,
)

PANEL_SHAPE = (64, 64)
CHOPPERS = ["wfm_chopper_1", "wfm_chopper_2"]
CHOPPER_GEOMETRY = [
    ChopperGeometry(
        name="wfm_chopper_1", distance_m=8.0, slit_edges_deg=((0.0, 100.0),)
    ),
    ChopperGeometry(
        name="wfm_chopper_2", distance_m=8.5, slit_edges_deg=((30.0, 140.0),)
    ),
]


from .streams_parsed import PARSED_STREAMS

INSTRUMENT = Instrument(
    name="tbl",
    streams=chopper_pv_streams(CHOPPERS, topic="tbl_choppers"),
    choppers=CHOPPERS,
    _factories_module="esslivedata_tpu.config.instruments.tbl.factories",
)
_n = PANEL_SHAPE[0] * PANEL_SHAPE[1]
INSTRUMENT.add_detector(
    DetectorConfig(
        name="panel",
        source_name="tbl_panel",
        detector_number=np.arange(1, _n + 1, dtype=np.int32).reshape(
            PANEL_SHAPE
        ),
        projection="logical",
    )
)
# --- The TBL detector zoo (reference specs.py:24-49) ------------------
# Timepix3: large panel folded logically to a displayable grid.
INSTRUMENT.add_detector(
    DetectorConfig(
        name="timepix3_detector",
        source_name="tbl_timepix3",
        detector_number=np.arange(1, 256 * 256 + 1, dtype=np.int32).reshape(
            256, 256
        ),
        projection="logical",
    )
)
# Multiblade: 14 blades x 32 wires x 64 strips, flat id space.
MULTIBLADE_SIZES = {"blade": 14, "wire": 32, "strip": 64}
_mb_shape = tuple(MULTIBLADE_SIZES.values())
_mb_n = int(np.prod(_mb_shape))
INSTRUMENT.add_detector(
    DetectorConfig(
        name="multiblade_detector",
        source_name="tbl_multiblade",
        detector_number=np.arange(1, _mb_n + 1, dtype=np.int32).reshape(
            _mb_shape[0], _mb_shape[1] * _mb_shape[2]
        ),
        projection="logical",
    )
)
# Two He3 tube banks: (tube, pixel) layout, disjoint id blocks.
HE3_SHAPE = (8, 512)
_he3_n = HE3_SHAPE[0] * HE3_SHAPE[1]
for _b in range(2):
    INSTRUMENT.add_detector(
        DetectorConfig(
            name=f"he3_detector_bank{_b}",
            source_name=f"tbl_he3_bank{_b}",
            detector_number=np.arange(
                1 + _b * _he3_n, 1 + (_b + 1) * _he3_n, dtype=np.int32
            ).reshape(HE3_SHAPE),
            projection="logical",
        )
    )
# nGEM: plain 2-D counts view.
INSTRUMENT.add_detector(
    DetectorConfig(
        name="ngem_detector",
        source_name="tbl_ngem",
        detector_number=np.arange(1, 128 * 128 + 1, dtype=np.int32).reshape(
            128, 128
        ),
        projection="logical",
    )
)
# ORCA camera: ad00 frames, no detector numbers (reference specs.py:30).
INSTRUMENT.add_camera(
    CameraConfig(name="orca_detector", source_name="tbl_orca")
)
INSTRUMENT.add_monitor(MonitorConfig(name="monitor", source_name="tbl_mon_1"))
INSTRUMENT.add_log("sample_temperature", "tbl_temp_1")
# The TBL monitor rides a translation stage: its position log drives
# the reset-on-move behavior of the monitor workflow.
INSTRUMENT.add_log("monitor_position", "tbl_mon_pos")
register_parsed_catalog(INSTRUMENT, PARSED_STREAMS)
instrument_registry.register(INSTRUMENT)

#: Multiblade view folds (blade, wire, strip) -> blade rows vs strip
#: columns, wires summed by the scatter (reference views.py:24).
MULTIBLADE_VIEW = NdLogicalView(
    sizes=MULTIBLADE_SIZES, y=("blade",), x=("strip",)
)


def _zoo_view_spec(name: str, title: str, sources: list[str]) -> WorkflowSpec:
    return WorkflowSpec(
        instrument="tbl",
        namespace="detector_view",
        name=name,
        title=title,
        source_names=sources,
        params_model=DetectorViewParams,
        outputs=detector_view_outputs(),
    )


PANEL_VIEW_HANDLE = workflow_registry.register_spec(
    _zoo_view_spec("panel_view", "Panel view", ["panel"])
)
TIMEPIX3_VIEW_HANDLE = workflow_registry.register_spec(
    _zoo_view_spec(
        "tbl_detector_timepix3", "Timepix3 XY counts", ["timepix3_detector"]
    )
)
MULTIBLADE_VIEW_HANDLE = workflow_registry.register_spec(
    _zoo_view_spec(
        "multiblade_detector_view",
        "Multiblade blade/strip view",
        ["multiblade_detector"],
    )
)
HE3_VIEW_HANDLE = workflow_registry.register_spec(
    _zoo_view_spec(
        "he3_detector_view",
        "He3 tube/pixel view",
        ["he3_detector_bank0", "he3_detector_bank1"],
    )
)
NGEM_VIEW_HANDLE = workflow_registry.register_spec(
    _zoo_view_spec(
        "ngem_detector_view", "nGEM 2-D counts", ["ngem_detector"]
    )
)
ORCA_VIEW_HANDLE = workflow_registry.register_spec(
    WorkflowSpec(
        instrument="tbl",
        namespace="detector_view",
        name="tbl_area_detector_orca",
        title="ORCA camera image",
        source_names=["orca_detector"],
        params_model=AreaDetectorParams,
        outputs={
            "current": OutputSpec(title="Frame (window)"),
            "cumulative": OutputSpec(
                title="Integrated image", view="since_start"
            ),
        },
    )
)

WAVELENGTH_LUT_HANDLE = workflow_registry.register_spec(
    WorkflowSpec(
        instrument="tbl",
        namespace="diagnostics",
        name="wavelength_lut",
        title="TOF->wavelength lookup table",
        source_names=["chopper_cascade"],
        params_model=WavelengthLutParams,
        context_keys=spec_context_keys(CHOPPER_GEOMETRY),
        reset_on_run_transition=False,
        outputs={
            "wavelength_lut": OutputSpec(title="Wavelength LUT"),
            "wavelength_bands": OutputSpec(title="Wavelength bands"),
        },
    )
)

MONITOR_HANDLE = register_monitor_spec(INSTRUMENT)
TIMESERIES_HANDLE = register_timeseries_spec(INSTRUMENT)

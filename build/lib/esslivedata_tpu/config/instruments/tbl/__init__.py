"""TBL: ESS test beamline — small panel, monitor, choppers (reference:
config/instruments/tbl)."""

from . import specs  # noqa: F401
from .specs import INSTRUMENT

__all__ = ["INSTRUMENT"]

"""Generated f144 stream registry — do not edit.

Regenerate: python scripts/generate_instrument_artifacts.py
Source artifact: geometry-tbl-<date>.nxs (synthesized)
"""

from esslivedata_tpu.config.stream import F144Stream

# (nexus_path, source, topic, units)
_ROWS: tuple[tuple[str, str, str, str | None], ...] = (
    ('/entry/instrument/chopper/delay', 'chopper:Delay', 'tbl_choppers', 'ns'),
    ('/entry/instrument/chopper/phase', 'chopper:Phs', 'tbl_choppers', 'deg'),
    ('/entry/instrument/chopper/rotation_speed', 'chopper:Spd', 'tbl_choppers', 'Hz'),
    ('/entry/instrument/chopper/rotation_speed_setpoint', 'chopper:SpdSet', 'tbl_choppers', 'Hz'),
    ('/entry/instrument/sample_stage/x/idle_flag', 'TBL-Smpl:MC-LinX-01:Mtr.DMOV', 'tbl_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/x/target_value', 'TBL-Smpl:MC-LinX-01:Mtr.VAL', 'tbl_motion', 'mm'),
    ('/entry/instrument/sample_stage/x/value', 'TBL-Smpl:MC-LinX-01:Mtr.RBV', 'tbl_motion', 'mm'),
    ('/entry/instrument/sample_stage/z/idle_flag', 'TBL-Smpl:MC-LinZ-01:Mtr.DMOV', 'tbl_motion', 'dimensionless'),
    ('/entry/instrument/sample_stage/z/target_value', 'TBL-Smpl:MC-LinZ-01:Mtr.VAL', 'tbl_motion', 'mm'),
    ('/entry/instrument/sample_stage/z/value', 'TBL-Smpl:MC-LinZ-01:Mtr.RBV', 'tbl_motion', 'mm'),
    ('/entry/sample/magnetic_field', 'TBL-SE:Mag-PSU-101', 'tbl_sample_env', 'T'),
    ('/entry/sample/pressure', 'TBL-SE:Prs-PIC-101', 'tbl_sample_env', 'bar'),
    ('/entry/sample/temperature_1', 'TBL-SE:Tmp-TIC-101', 'tbl_sample_env', 'K'),
)

PARSED_STREAMS: dict[str, F144Stream] = {
    path: F144Stream(nexus_path=path, source=source, topic=topic, units=units)
    for path, source, topic, units in _ROWS
}

"""TBL factories."""

from __future__ import annotations

from functools import lru_cache

from ....workflows.area_detector_view import AreaDetectorView
from ....workflows.detector_view.projectors import (
    ProjectionTable,
    project_logical,
    project_logical_nd,
)
from ....workflows.detector_view.workflow import DetectorViewWorkflow
from ....workflows.monitor_workflow import MonitorWorkflow
from ....workflows.timeseries import TimeseriesWorkflow
from ....workflows.wavelength_lut_workflow import WavelengthLutWorkflow
from .specs import (
    CHOPPER_GEOMETRY,
    HE3_VIEW_HANDLE,
    INSTRUMENT,
    MONITOR_HANDLE,
    MULTIBLADE_VIEW,
    MULTIBLADE_VIEW_HANDLE,
    NGEM_VIEW_HANDLE,
    ORCA_VIEW_HANDLE,
    PANEL_VIEW_HANDLE,
    TIMEPIX3_VIEW_HANDLE,
    TIMESERIES_HANDLE,
    WAVELENGTH_LUT_HANDLE,
)


@lru_cache(maxsize=None)
def _logical_projection(name: str) -> ProjectionTable:
    return project_logical(INSTRUMENT.detectors[name].detector_number)


def _logical_view_factory():
    def factory(*, source_name: str, params) -> DetectorViewWorkflow:
        return DetectorViewWorkflow(
            projection=_logical_projection(source_name),
            params=params,
            primary_stream=source_name,
        )

    return factory


make_panel_view = PANEL_VIEW_HANDLE.attach_factory(_logical_view_factory())
make_timepix3_view = TIMEPIX3_VIEW_HANDLE.attach_factory(
    _logical_view_factory()
)
make_he3_view = HE3_VIEW_HANDLE.attach_factory(_logical_view_factory())
make_ngem_view = NGEM_VIEW_HANDLE.attach_factory(_logical_view_factory())


@lru_cache(maxsize=None)
def _multiblade_projection() -> ProjectionTable:
    return project_logical_nd(
        INSTRUMENT.detectors["multiblade_detector"].detector_number,
        MULTIBLADE_VIEW,
    )


@MULTIBLADE_VIEW_HANDLE.attach_factory
def make_multiblade_view(*, source_name: str, params) -> DetectorViewWorkflow:  # noqa: ARG001
    return DetectorViewWorkflow(
        projection=_multiblade_projection(), params=params
    )


@ORCA_VIEW_HANDLE.attach_factory
def make_orca_view(*, source_name: str, params) -> AreaDetectorView:  # noqa: ARG001
    return AreaDetectorView(params=params)


@WAVELENGTH_LUT_HANDLE.attach_factory
def make_wavelength_lut(*, source_name: str, params) -> WavelengthLutWorkflow:  # noqa: ARG001
    return WavelengthLutWorkflow(choppers=CHOPPER_GEOMETRY, params=params)


@MONITOR_HANDLE.attach_factory
def make_monitor(*, source_name: str, params) -> MonitorWorkflow:
    return MonitorWorkflow(
        params=params, position_stream=f"{source_name}_position"
    )


@TIMESERIES_HANDLE.attach_factory
def make_timeseries(*, source_name: str, params) -> TimeseriesWorkflow:  # noqa: ARG001
    return TimeseriesWorkflow()

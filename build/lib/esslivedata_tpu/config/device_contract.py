"""Per-instrument NICOS derived-device contract, derived from the registry.

Parity with reference ``config/device_contract.py`` (ADR 0006): a NICOS
*derived device* is a scalar cumulative workflow output exposed under a
stable device name. The mapping

    (workflow_id, source_name, output_name) -> device_name

is derived from the workflow registry: an output is a device iff its
``WorkflowSpec`` lists it in ``device_outputs``, with the device name
rendered from the declared template per source. Because the contract is
generated from the registry it cannot drift from it; the one remaining
failure mode — a template rendering the same device name twice — fails loud
at construction. ``to_yaml``/``from_yaml`` provide the static git-tracked
export NICOS consumes.

NICOS resets a device by producing a ``{"kind": "job_command", "action":
"reset", "workflow_id": ...}`` command; the reset is confirmed by the
``start_time`` generation jump on the device topic, not by an ack.
"""

from __future__ import annotations

from collections.abc import Iterable, Iterator, Mapping
from typing import TYPE_CHECKING

from pydantic import BaseModel

from .workflow_spec import WorkflowSpec

if TYPE_CHECKING:
    from .workflow_spec import WorkflowId

__all__ = ["DeviceContract", "DeviceContractEntry", "DeviceContractError"]


class DeviceContractError(ValueError):
    """Raised when a contract renders duplicate or invalid device names."""


class DeviceContractEntry(BaseModel, frozen=True):
    """One ``(workflow_id, source_name, output_name) -> device_name`` row."""

    workflow_id: str
    source_name: str
    output_name: str
    device_name: str

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.workflow_id, self.source_name, self.output_name)


def _render(template: str, source_name: str) -> str:
    try:
        return template.format(source_name=source_name)
    except (KeyError, IndexError) as exc:
        raise DeviceContractError(
            f"Invalid device_name template {template!r}: {exc}"
        ) from exc


class DeviceContract:
    """Validated, immutable set of device-contract entries."""

    def __init__(self, entries: Iterable[DeviceContractEntry]) -> None:
        self._entries = tuple(entries)
        seen_keys: set[tuple[str, str, str]] = set()
        seen_names: dict[str, DeviceContractEntry] = {}
        # (workflow_id, source_name) -> entries: the per-result lookup the
        # extractor does every batch, precomputed so it is O(1).
        self._by_job: dict[tuple[str, str], list[DeviceContractEntry]] = {}
        for entry in self._entries:
            if entry.key in seen_keys:
                raise DeviceContractError(
                    f"Duplicate device-contract key {entry.key}"
                )
            if entry.device_name in seen_names:
                other = seen_names[entry.device_name]
                raise DeviceContractError(
                    f"Device name {entry.device_name!r} rendered by both "
                    f"{other.key} and {entry.key}"
                )
            seen_keys.add(entry.key)
            seen_names[entry.device_name] = entry
            self._by_job.setdefault(
                (entry.workflow_id, entry.source_name), []
            ).append(entry)

    @classmethod
    def from_specs(cls, specs: Iterable[WorkflowSpec]) -> DeviceContract:
        """Derive the contract from workflow specs (the single source of
        truth); specs without ``device_outputs`` contribute nothing."""
        entries = []
        for spec in specs:
            wid = str(spec.identifier)
            for output_name, template in spec.device_outputs.items():
                for source in spec.source_names:
                    entries.append(
                        DeviceContractEntry(
                            workflow_id=wid,
                            source_name=source,
                            output_name=output_name,
                            device_name=_render(template, source),
                        )
                    )
        return cls(entries)

    def __iter__(self) -> Iterator[DeviceContractEntry]:
        return iter(self._entries)

    def __len__(self) -> int:
        return len(self._entries)

    def devices_for(
        self, workflow_id: WorkflowId | str, source_name: str
    ) -> tuple[DeviceContractEntry, ...]:
        """Entries for one (workflow, source) pair."""
        return tuple(self._by_job.get((str(workflow_id), source_name), ()))

    def to_mapping(self) -> list[dict[str, str]]:
        """JSON/YAML-ready export, sorted for stable diffs."""
        return [
            e.model_dump() for e in sorted(self._entries, key=lambda e: e.key)
        ]

    @classmethod
    def from_mapping(cls, rows: Iterable[Mapping[str, str]]) -> DeviceContract:
        return cls(DeviceContractEntry.model_validate(row) for row in rows)


def contract_to_yaml(contract: DeviceContract, *, instrument: str) -> str:
    """The static git-tracked YAML export NICOS consumes (one file per
    instrument package, regenerated by
    ``scripts/generate_instrument_artifacts.py``)."""
    import yaml

    header = (
        f"# GENERATED -- do not edit. NICOS derived-device list for "
        f"{instrument}.\n"
        "# Regenerate: python scripts/generate_instrument_artifacts.py\n"
    )
    return header + yaml.safe_dump(
        {"devices": contract.to_mapping()}, sort_keys=False
    )


def contract_from_yaml(text: str) -> DeviceContract:
    import yaml

    data = yaml.safe_load(text) or {}
    return DeviceContract.from_mapping(data.get("devices", []))


def load_instrument_contract(instrument: str) -> DeviceContract:
    """The checked-in contract of a built-in instrument package."""
    from importlib import resources

    pkg = f"esslivedata_tpu.config.instruments.{instrument}"
    text = (
        resources.files(pkg).joinpath("device_contract.yaml").read_text()
    )
    return contract_from_yaml(text)

"""Declarative NeXus-artifact plans for every built-in instrument.

Each plan describes the instrument's NeXus file at the fidelity the
framework consumes: detector banks (geometry or logical layout), monitors,
choppers, motorised devices and sample-environment logs, each carrying the
file-writer stream declaration (topic/source/writer_module). Source names
of banks and monitors match the instrument's ``specs.py`` so the generated
stream registry and the hand-declared event routing agree.

These plans stand in for real ESS geometry files (which a deployment
fetches into ``LIVEDATA_DATA_DIR``; reference
preprocessors/detector_data.py:66-127): the synthesized file has the same
structure, so swapping a real artifact in requires no code change.
Group paths and EPICS PV spellings in the plans are deliberately this
codebase's own *placeholders*, not transcriptions of facility names: a
deployment installs the real geometry file
(``scripts/fetch_geometry.py install``) and regenerates the registries
from it (``scripts/generate_instrument_artifacts.py`` /
``python -m esslivedata_tpu.config.nexus_streams``), which restores the
facility's actual paths and sources end to end.

PV naming follows the EPICS motor-record convention (``<base>.RBV`` /
``.VAL`` / ``.DMOV``) that stream.name_streams device detection keys on.
"""

from __future__ import annotations

from .nexus_synthesis import (
    BankPlan,
    ChopperPlan,
    DevicePlan,
    InstrumentNexusPlan,
    LogPlan,
    MonitorPlan,
)

__all__ = ["NEXUS_PLANS", "plan_for"]


def _slit(group: str, pv_base: str, topic: str) -> tuple[DevicePlan, ...]:
    """A 4-axis slit: horizontal/vertical gap + centre."""
    return tuple(
        DevicePlan(
            group=f"{group}/{axis}",
            pv=f"{pv_base}-{tag}-01:Mtr",
            topic=topic,
        )
        for axis, tag in (
            ("x_gap", "SlGapX"),
            ("y_gap", "SlGapY"),
            ("x_center", "SlCenX"),
            ("y_center", "SlCenY"),
        )
    )


def _stage(
    group: str, pv_base: str, topic: str, axes: tuple[tuple[str, str, str], ...]
) -> tuple[DevicePlan, ...]:
    """A multi-axis stage; axes = (group_leaf, pv_tag, units)."""
    return tuple(
        DevicePlan(
            group=f"{group}/{leaf}",
            pv=f"{pv_base}-{tag}-01:Mtr",
            topic=topic,
            units=units,
        )
        for leaf, tag, units in axes
    )


_XYZ_OMEGA = (
    ("x", "LinX", "mm"),
    ("y", "LinY", "mm"),
    ("z", "LinZ", "mm"),
    ("omega", "RotZ", "deg"),
)


def _sample_env(instrument: str, n_temp: int = 2) -> tuple[LogPlan, ...]:
    """Typical sample-environment block: temperatures, pressure, field."""
    topic = f"{instrument}_sample_env"
    logs = [
        LogPlan(
            group=f"sample/temperature_{i}",
            source=f"{instrument.upper()}-SE:Tmp-TIC-{100 + i}",
            topic=topic,
            units="K",
        )
        for i in range(1, n_temp + 1)
    ]
    logs.append(
        LogPlan(
            group="sample/pressure",
            source=f"{instrument.upper()}-SE:Prs-PIC-101",
            topic=topic,
            units="bar",
        )
    )
    logs.append(
        LogPlan(
            group="sample/magnetic_field",
            source=f"{instrument.upper()}-SE:Mag-PSU-101",
            topic=topic,
            units="T",
        )
    )
    return tuple(logs)


def _vacuum(instrument: str, n: int = 4) -> tuple[LogPlan, ...]:
    """Vacuum gauges on an *unauthorized* topic: these exercise
    ``filter_authorized_streams`` (the ``_vacuum`` topic has no PROD ACL
    grant, so registry consumers must drop them)."""
    return tuple(
        LogPlan(
            group=f"vacuum/gauge_{i}",
            source=f"{instrument.upper()}-Vac:VGP-{i:03d}",
            topic=f"{instrument}_vacuum",
            units="mbar",
        )
        for i in range(1, n + 1)
    )


_LOKI = InstrumentNexusPlan(
    name="loki",
    title="LOKI small-angle scattering",
    banks=(
        BankPlan(
            name="larmor_detector",
            source="loki_rear_detector",
            topic="loki_detector",
            shape=(256, 256),
            extent=(1.0, 1.0),
            z=5.0,
        ),
    ),
    monitors=tuple(
        MonitorPlan(
            name=f"beam_monitor_{i}",
            source=f"loki_mon_{i}",
            topic="loki_monitor",
            z=-2.0 - i,
            positioner_pv=f"LOKI-BMon{i}:MC-LinZ-01:Mtr",
            positioner_topic="loki_motion",
        )
        for i in range(5)
    ),
    choppers=(
        ChopperPlan(name="bandwidth_chopper_1", pv="LOKI-Chop:BWC-01", topic="loki_choppers"),
        ChopperPlan(name="bandwidth_chopper_2", pv="LOKI-Chop:BWC-02", topic="loki_choppers"),
    ),
    devices=(
        *_slit("collimation_slit_1", "LOKI-ColSl1:MC", "loki_motion"),
        *_slit("collimation_slit_2", "LOKI-ColSl2:MC", "loki_motion"),
        *_slit("collimation_slit_3", "LOKI-ColSl3:MC", "loki_motion"),
        *_slit("sample_slit", "LOKI-SmplSl:MC", "loki_motion"),
        *_stage("sample_stage", "LOKI-Smpl:MC", "loki_motion", _XYZ_OMEGA),
        DevicePlan(
            group="detector_carriage/z",
            pv="LOKI-DetCar:MC-LinZ-01:Mtr",
            topic="loki_motion",
            units="m",
        ),
        DevicePlan(
            group="transmission_flag/state",
            pv="LOKI-TrFlag:MC-RotY-01:Mtr",
            topic="loki_motion",
            units="deg",
            with_target=False,
        ),
    ),
    logs=(
        *_sample_env("loki", n_temp=4),
        *_vacuum("loki"),
        *(
            LogPlan(
                group=f"detector_env/bank_temperature_{i}",
                source=f"LOKI-Det:Tmp-TIC-{200 + i}",
                topic="loki_sample_env",
                units="K",
            )
            for i in range(1, 10)
        ),
    ),
)


_DREAM_BANKS = {
    # (flattened shapes matching specs.BANK_SIZES products; the file keeps
    # the full N-d layout so logical views can index named axes)
    "mantle_detector": (32, 5, 6, 256, 2),
    "endcap_backward_detector": (16, 16, 11, 28, 2),
    "endcap_forward_detector": (16, 16, 5, 28, 2),
    "high_resolution_detector": (32, 16, 3, 20, 2),
    "sans_detector": (32, 16, 3, 10, 2),
}

_DREAM = InstrumentNexusPlan(
    name="dream",
    title="DREAM powder diffractometer",
    banks=(),  # filled by _with_contiguous_bank_ids below
    monitors=(
        MonitorPlan(
            name="monitor_bunker",
            source="dream_mon_bunker",
            topic="dream_monitor",
            z=-18.0,
        ),
        MonitorPlan(
            name="monitor_cave",
            source="dream_mon_cave",
            topic="dream_monitor",
            z=-1.5,
            positioner_pv="DREAM-MonC:MC-LinZ-01:Mtr",
            positioner_topic="dream_motion",
        ),
    ),
    choppers=(
        ChopperPlan(name="pulse_shaping_chopper1", pv="pulse_shaping_chopper1", topic="dream_choppers"),
        ChopperPlan(name="pulse_shaping_chopper2", pv="pulse_shaping_chopper2", topic="dream_choppers"),
        ChopperPlan(name="band_chopper", pv="band_chopper", topic="dream_choppers"),
        ChopperPlan(name="overlap_chopper", pv="overlap_chopper", topic="dream_choppers"),
        ChopperPlan(name="T0_chopper", pv="T0_chopper", topic="dream_choppers"),
    ),
    devices=(
        *_slit("divergence_slit", "DREAM-DivSl:MC", "dream_motion"),
        *_stage("sample_stage", "DREAM-Smpl:MC", "dream_motion", _XYZ_OMEGA),
        *_stage(
            "collimator",
            "DREAM-Coll:MC",
            "dream_motion",
            (("rotation", "RotZ", "deg"), ("z", "LinZ", "mm")),
        ),
        DevicePlan(
            group="polarizer/state",
            pv="DREAM-Pol:MC-LinX-01:Mtr",
            topic="dream_motion",
        ),
    ),
    logs=(
        *_sample_env("dream", n_temp=3),
        *_vacuum("dream", n=3),
    ),
)


def _with_contiguous_bank_ids(
    plan: InstrumentNexusPlan, banks: dict[str, tuple[int, ...]]
) -> InstrumentNexusPlan:
    """Rebuild a plan's banks with contiguous first_ids, matching the
    ``arange``-per-bank layout the instrument specs declare."""
    import dataclasses

    import numpy as np

    out = []
    offset = 1
    for name, shape in banks.items():
        out.append(
            BankPlan(
                name=name,
                source=f"{plan.name}_{name}",
                topic=f"{plan.name}_detector",
                shape=shape,
                first_id=offset,
                logical=True,
            )
        )
        offset += int(np.prod(shape))
    return dataclasses.replace(plan, banks=tuple(out))


_DREAM = _with_contiguous_bank_ids(_DREAM, _DREAM_BANKS)


_BIFROST = _with_contiguous_bank_ids(
    InstrumentNexusPlan(
        name="bifrost",
        title="BIFROST indirect-geometry spectrometer",
        monitors=(
            MonitorPlan(
                name="monitor_1",
                source="bifrost_mon_1",
                topic="bifrost_monitor",
                z=-2.0,
            ),
        ),
        choppers=(
            ChopperPlan(name="pulse_shaping_chopper", pv="BIFR-Chop:PSC-01", topic="bifrost_choppers"),
            ChopperPlan(name="frame_overlap_chopper", pv="BIFR-Chop:FOC-01", topic="bifrost_choppers"),
        ),
        devices=(
            *_stage("sample_stage", "BIFR-Smpl:MC", "bifrost_motion", _XYZ_OMEGA),
            *(
                DevicePlan(
                    group=f"analyzer_{i}/goniometer",
                    pv=f"BIFR-Ana{i}:MC-RotX-01:Mtr",
                    topic="bifrost_motion",
                    units="deg",
                )
                for i in range(1, 10)
            ),
        ),
        logs=(
            *_sample_env("bifrost"),
            *(
                LogPlan(
                    group=f"analyzer_env/temperature_{i}",
                    source=f"BIFR-Ana:Tmp-TIC-{i:03d}",
                    topic="bifrost_sample_env",
                    units="K",
                )
                for i in range(1, 10)
            ),
        ),
    ),
    {f"triplet_{i}": (100, 30) for i in range(9)},
)


_ESTIA = InstrumentNexusPlan(
    name="estia",
    title="ESTIA reflectometer",
    banks=(
        BankPlan(
            name="multiblade_detector",
            source="estia_multiblade",
            topic="estia_detector",
            shape=(48, 32, 64),
            logical=True,
        ),
    ),
    monitors=(
        MonitorPlan(
            name="cbm1", source="estia_cbm1", topic="estia_monitor", z=-1.0
        ),
    ),
    choppers=(
        ChopperPlan(name="chopper_1", pv="ESTIA-Chop:C1", topic="estia_choppers"),
        ChopperPlan(name="chopper_2", pv="ESTIA-Chop:C2", topic="estia_choppers"),
    ),
    devices=(
        *_slit("slit_1", "ESTIA-Sl1:MC", "estia_motion"),
        *_slit("slit_2", "ESTIA-Sl2:MC", "estia_motion"),
        *_stage(
            "sample_stage",
            "ESTIA-Smpl:MC",
            "estia_motion",
            (*_XYZ_OMEGA, ("chi", "RotX", "deg")),
        ),
        DevicePlan(
            group="detector_arm/two_theta",
            pv="ESTIA-DetArm:MC-RotZ-01:Mtr",
            topic="estia_motion",
            units="deg",
        ),
    ),
    logs=_sample_env("estia"),
)


_NMX = _with_contiguous_bank_ids(
    InstrumentNexusPlan(
        name="nmx",
        title="NMX macromolecular diffractometer",
        monitors=(
            MonitorPlan(name="monitor1", source="nmx_mon_1", topic="nmx_monitor", z=-3.0),
            MonitorPlan(name="monitor2", source="nmx_mon_2", topic="nmx_monitor", z=-0.5),
        ),
        choppers=(
            ChopperPlan(name="chopper_1", pv="NMX-Chop:C1", topic="nmx_choppers"),
        ),
        devices=(
            *_stage("sample_stage", "NMX-Smpl:MC", "nmx_motion", _XYZ_OMEGA),
            *(
                d
                for i in range(3)
                for d in _stage(
                    f"detector_panel_{i}",
                    f"NMX-Det{i}:MC",
                    "nmx_motion",
                    (("distance", "LinZ", "m"), ("rotation", "RotZ", "deg")),
                )
            ),
        ),
        logs=_sample_env("nmx"),
    ),
    {f"detector_panel_{i}": (1280, 1280) for i in range(3)},
)


def _blade_slit(group: str, pv_base: str, topic: str) -> tuple[DevicePlan, ...]:
    """A 6-axis collimation slit: gap/centre per direction plus the two
    individually motorized vertical blades (the ym/yp pattern imaging
    beamlines use for asymmetric collimation)."""
    return (
        *_slit(group, pv_base, topic),
        DevicePlan(group=f"{group}/ym", pv=f"{pv_base}-BldYm-01:Mtr", topic=topic),
        DevicePlan(group=f"{group}/yp", pv=f"{pv_base}-BldYp-01:Mtr", topic=topic),
    )


# ODIN is the cardinality proof: the registry pipeline (synthesis ->
# parse -> authorization filter -> naming -> device detection -> route
# derivation) runs at the reference's real scale (~280 f144 streams:
# 10 choppers, ~66 motorized axes, sample-env/vacuum/beam logs).
_ODIN = InstrumentNexusPlan(
    name="odin",
    title="ODIN imaging beamline",
    banks=(
        BankPlan(
            name="timepix3",
            source="odin_timepix3",
            topic="odin_detector",
            shape=(512, 512),
            logical=True,
        ),
    ),
    monitors=(
        MonitorPlan(name="monitor1", source="odin_mon_1", topic="odin_monitor", z=-10.0),
        MonitorPlan(name="monitor2", source="odin_mon_2", topic="odin_monitor", z=-0.2),
    ),
    choppers=(
        # WFM pair + band-pass pair + five frame-overlap choppers + T0:
        # the reference ODIN cascade's composition.
        *(
            ChopperPlan(name=f"wfm_chopper_{i}", pv=f"ODIN-Chop:WFM-{i:02d}", topic="odin_choppers")
            for i in (1, 2)
        ),
        *(
            ChopperPlan(name=f"bpc_chopper_{i}", pv=f"ODIN-Chop:BPC-{i:02d}", topic="odin_choppers")
            for i in (1, 2)
        ),
        *(
            ChopperPlan(name=f"foc_chopper_{i}", pv=f"ODIN-Chop:FOC-{i:02d}", topic="odin_choppers")
            for i in range(1, 6)
        ),
        ChopperPlan(name="t0_chopper", pv="ODIN-Chop:T0-01", topic="odin_choppers"),
    ),
    devices=(
        *_stage(
            "sample_stage",
            "ODIN-Smpl:MC",
            "odin_motion",
            (
                *_XYZ_OMEGA,
                ("phi", "RotX", "deg"),
                ("tilt", "RotY", "deg"),
            ),
        ),
        DevicePlan(
            group="heavy_shutter",
            pv="ODIN-Shtr:MC-Lin-01:Mtr",
            topic="odin_motion",
        ),
        # Two camera boxes, each with its own optics axes.
        *(
            plan
            for i in (1, 2)
            for plan in _stage(
                f"camera{i}",
                f"ODIN-Cam{i}:MC",
                "odin_motion",
                (
                    ("distance", "LinZ", "mm"),
                    ("focus", "LinF", "mm"),
                    ("rotation", "Rot", "deg"),
                ),
            )
        ),
        # ANC piezo cluster at the sample position.
        DevicePlan(group="anc_goniometer", pv="ODIN-ANC:MC-Gon-01:Mtr", topic="odin_motion", units="deg"),
        DevicePlan(group="anc_rotary", pv="ODIN-ANC:MC-Rot-01:Mtr", topic="odin_motion", units="deg"),
        DevicePlan(group="anc_linear_1", pv="ODIN-ANC:MC-Lin-01:Mtr", topic="odin_motion"),
        DevicePlan(group="anc_linear_2", pv="ODIN-ANC:MC-Lin-02:Mtr", topic="odin_motion"),
        # Four 6-axis collimation slit packages along the guide.
        *(
            plan
            for i in (1, 2, 3, 4)
            for plan in _blade_slit(
                f"col_slit_{i}", f"ODIN-ColS{i}:MC", "odin_motion"
            )
        ),
        *_slit("pinhole_selector", "ODIN-PinH:MC", "odin_motion"),
        # Two aperture diaphragms near the detector.
        *(
            plan
            for i in (1, 2)
            for plan in _slit(f"diaphragm_{i}", f"ODIN-Diaph{i}:MC", "odin_motion")
        ),
        DevicePlan(group="filter_changer_1", pv="ODIN-Filt:MC-Whl-01:Mtr", topic="odin_motion", units="deg"),
        DevicePlan(group="filter_changer_2", pv="ODIN-Filt:MC-Whl-02:Mtr", topic="odin_motion", units="deg"),
        *_stage(
            "detector_stage",
            "ODIN-Det:MC",
            "odin_motion",
            (("x", "LinX", "mm"), ("z", "LinZ", "mm"), ("rotation", "Rot", "deg")),
        ),
        DevicePlan(group="beam_stop/x", pv="ODIN-BStp:MC-LinX-01:Mtr", topic="odin_motion"),
        DevicePlan(group="beam_stop/y", pv="ODIN-BStp:MC-LinY-01:Mtr", topic="odin_motion"),
        DevicePlan(group="attenuator_wheel_1", pv="ODIN-Att:MC-Whl-01:Mtr", topic="odin_motion", units="deg"),
        DevicePlan(group="attenuator_wheel_2", pv="ODIN-Att:MC-Whl-02:Mtr", topic="odin_motion", units="deg"),
        DevicePlan(group="polarizer/rotation", pv="ODIN-Pol:MC-Rot-01:Mtr", topic="odin_motion", units="deg"),
        DevicePlan(group="polarizer/translation", pv="ODIN-Pol:MC-Lin-01:Mtr", topic="odin_motion"),
        DevicePlan(group="grating_stage/x", pv="ODIN-Grt:MC-LinX-01:Mtr", topic="odin_motion"),
        DevicePlan(group="grating_stage/z", pv="ODIN-Grt:MC-LinZ-01:Mtr", topic="odin_motion"),
    ),
    logs=(
        *_sample_env("odin", n_temp=4),
        *_vacuum("odin", n=8),
        # Beam diagnostics on the general-data topic (authorized).
        *(
            LogPlan(
                group=f"beam_monitoring/{name}",
                source=f"ODIN-Beam:{pv}",
                topic="tn_data_general",
                units=units,
            )
            for name, pv, units in (
                ("proton_current", "PBI-ICT-001", "uA"),
                ("proton_charge", "PBI-ICT-002", "uC"),
                ("target_temperature", "Tgt-TT-001", "K"),
                ("moderator_temperature", "Mod-TT-001", "K"),
            )
        ),
        # Helium-3 polarization cell telemetry.
        *(
            LogPlan(
                group=f"polarizer/{name}",
                source=f"ODIN-Pol:SE-{pv}",
                topic="odin_sample_env",
                units=units,
            )
            for name, pv, units in (
                ("cell_polarization", "Pol-001", "dimensionless"),
                ("cell_temperature", "TT-001", "K"),
            )
        ),
    ),
)


_TBL = InstrumentNexusPlan(
    name="tbl",
    title="TBL test beamline",
    banks=(
        BankPlan(
            name="panel",
            source="tbl_panel",
            topic="tbl_detector",
            shape=(64, 64),
            logical=True,
        ),
    ),
    monitors=(
        MonitorPlan(name="monitor", source="tbl_mon_1", topic="tbl_monitor", z=-1.0),
    ),
    choppers=(
        ChopperPlan(name="chopper", pv="chopper", topic="tbl_choppers"),
    ),
    devices=(
        *_stage(
            "sample_stage",
            "TBL-Smpl:MC",
            "tbl_motion",
            (("x", "LinX", "mm"), ("z", "LinZ", "mm")),
        ),
    ),
    logs=_sample_env("tbl", n_temp=1),
)


_DUMMY = InstrumentNexusPlan(
    name="dummy",
    title="Dummy development instrument",
    banks=(
        BankPlan(
            name="panel_0",
            source="panel_a",
            topic="dummy_detector",
            shape=(64, 64),
            logical=True,
        ),
    ),
    monitors=(
        MonitorPlan(name="monitor_1", source="mon_src", topic="dummy_monitor", z=-1.0),
    ),
    devices=(
        # NB: not named motor_x — that name is the hand-declared log stream
        # in dummy/specs.py and the two must stay distinct in the LUT.
        DevicePlan(
            group="sample_changer/position",
            pv="DMY-MC:SmplPos",
            topic="dummy_motion",
        ),
    ),
    logs=_sample_env("dummy", n_temp=1),
)


NEXUS_PLANS: dict[str, InstrumentNexusPlan] = {
    p.name: p
    for p in (
        _LOKI,
        _DREAM,
        _BIFROST,
        _ESTIA,
        _NMX,
        _ODIN,
        _TBL,
        _DUMMY,
    )
}


def plan_for(instrument: str) -> InstrumentNexusPlan:
    try:
        return NEXUS_PLANS[instrument]
    except KeyError:
        raise KeyError(
            f"No NeXus plan for instrument {instrument!r}; "
            f"known: {sorted(NEXUS_PLANS)}"
        ) from None

"""Chopper stream-name conventions (instrument-level data-model concern).

Parity with reference ``config/chopper.py``: an instrument that declares
choppers owns the streams they produce — a clean ``rotation_speed_setpoint``
and a noisy ``delay`` readback per chopper (real upstream PVs), plus the
synthetic ``delay_setpoint`` the ``ChopperSynthesizer`` derives by plateau
detection. The wavelength-LUT workflow consumes these as context; it is not
their owner.
"""

from __future__ import annotations

from collections.abc import Sequence

from .stream import F144Stream, Stream

__all__ = [
    "CHOPPER_CASCADE_SOURCE",
    "chopper_pv_streams",
    "declare_chopper_setpoint_streams",
    "delay_readback_stream",
    "delay_setpoint_stream",
    "speed_setpoint_stream",
]

#: Logical source name of the synthetic cascade trigger stream: emitted by
#: ChopperSynthesizer once every chopper locks; consumed as the wavelength-
#: LUT workflow's primary dynamic stream (its arrival drives a recompute).
CHOPPER_CASCADE_SOURCE = "chopper_cascade"


def speed_setpoint_stream(chopper: str) -> str:
    """Stream name of a chopper's clean rotation-speed setpoint f144 PV."""
    return f"{chopper}/rotation_speed_setpoint"


def delay_readback_stream(chopper: str) -> str:
    """Stream name of a chopper's noisy delay readback f144 PV."""
    return f"{chopper}/delay"


def delay_setpoint_stream(chopper: str) -> str:
    """Stream name of the synthesized (plateau-locked) delay setpoint.

    Emitted in-process by ``ChopperSynthesizer``; not a Kafka topic.
    """
    return f"{chopper}/delay_setpoint"


def chopper_pv_streams(
    choppers: Sequence[str], *, topic: str, source_prefix: str = ""
) -> dict[str, Stream]:
    """Catalog entries for each chopper's real upstream PVs.

    One speed-setpoint and one delay-readback F144Stream per chopper, named
    by the same helpers route derivation subscribes through — instruments
    use this instead of hand-building the names so declaration and
    subscription can never desynchronize.
    """
    streams: dict[str, Stream] = {}
    for chopper in choppers:
        prefix = source_prefix or chopper
        streams[speed_setpoint_stream(chopper)] = F144Stream(
            topic=topic, source=f"{prefix}:SpdSet", units="Hz"
        )
        streams[delay_readback_stream(chopper)] = F144Stream(
            topic=topic, source=f"{prefix}:Delay", units="ns"
        )
    return streams


def declare_chopper_setpoint_streams(
    streams: dict[str, Stream], choppers: Sequence[str]
) -> None:
    """Declare the synthetic ``delay_setpoint`` streams in-place.

    The readback must carry unit 'ns': plateau detection and the delay
    tolerance threshold assume nanosecond samples, so a differently-unitted
    readback would silently mis-scale detection.
    """
    for chopper in choppers:
        try:
            readback = streams[delay_readback_stream(chopper)]
        except KeyError:
            raise ValueError(
                f"Chopper {chopper!r} declared but its delay readback stream "
                f"{delay_readback_stream(chopper)!r} is not in the stream "
                f"catalog"
            ) from None
        units = getattr(readback, "units", None)
        if units != "ns":
            raise ValueError(
                f"Chopper {chopper!r} delay readback declares units "
                f"{units!r}, expected 'ns'"
            )
        name = delay_setpoint_stream(chopper)
        if (existing := streams.get(name)) is not None:
            if existing.topic is not None:
                raise ValueError(
                    f"Stream {name!r} already declared with a Kafka identity "
                    f"(topic={existing.topic!r}); the synthesizer would "
                    "shadow a real upstream PV"
                )
            continue  # idempotent re-declaration of the synthetic stream
        streams[name] = F144Stream(units=units)

"""Declarative configuration: instruments, workflow specs, stream mappings.

Parity with reference ``src/ess/livedata/config/`` (SURVEY.md section 2.6).
Everything above and below reads this layer; it has no dependencies on the
runtime. Workflow parameter models are pydantic and double as the dashboard
UI schema, exactly like the reference (workflow_spec.py:312-398).
"""

from .workflow_spec import (
    JobId,
    JobSchedule,
    OutputSpec,
    ResultKey,
    WorkflowConfig,
    WorkflowId,
    WorkflowSpec,
)

__all__ = [
    "JobId",
    "JobSchedule",
    "OutputSpec",
    "ResultKey",
    "WorkflowConfig",
    "WorkflowId",
    "WorkflowSpec",
]

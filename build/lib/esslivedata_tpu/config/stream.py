"""Stream records: what flows on the wire, independent of what it's called.

Every piece of live data a service can consume — detector event streams,
monitor streams, f144 sample-environment logs, synthesized motor devices —
is declared as one record here. Records carry wire identity only (schema,
Kafka coordinates, NeXus origin). Instrument-facing *names* are assigned
separately by :func:`name_streams`, and those names are what the rest of
the system routes on; Kafka topic/source matter solely at the byte
boundary where messages arrive.

Field names (``writer_module``/``nexus_path``/``topic``/``source``/
``nx_class``; ``value``/``target``/``idle`` for devices) are the shared
domain vocabulary of the ESS streaming stack (cf. reference
``config/stream.py``) and are kept so generated registries read the same;
everything else — validation, naming, device detection — is this
codebase's own design.

Construction is fail-fast: a malformed record or a name collision raises
while the instrument module imports, never at message time.
"""

from __future__ import annotations

from collections.abc import Iterable, Mapping
from dataclasses import dataclass

__all__ = [
    "ContextBinding",
    "Device",
    "F144Stream",
    "Stream",
    "filter_authorized_streams",
    "name_streams",
    "suggest_names",
]


@dataclass(frozen=True, slots=True, kw_only=True)
class Stream:
    """One streaming data declaration at the wire level.

    Three shapes are legal:

    * **Kafka-borne** — ``topic`` and ``source`` both set; ``nexus_path``
      optional (hand-written registry rows may predate a geometry file).
    * **In-process** — all three None. Produced by synthesizers; bytes for
      these never exist on a broker.
    * Anything with exactly one of ``topic``/``source`` set is a broken
      declaration and is rejected here rather than surfacing later as an
      unroutable message.
    """

    writer_module: str
    nexus_path: str | None = None
    topic: str | None = None
    source: str | None = None
    nx_class: str = ""

    def __post_init__(self) -> None:
        if (self.topic is None) != (self.source is None):
            where = self.nexus_path or "<in-process>"
            raise ValueError(
                f"stream at {where}: kafka identity is all-or-nothing, got "
                f"topic={self.topic!r} with source={self.source!r}"
            )


@dataclass(frozen=True, slots=True, kw_only=True)
class F144Stream(Stream):
    """Scalar log stream (f144 schema): timestamped numeric samples."""

    units: str | None = None
    writer_module: str = "f144"
    nx_class: str = "NXlog"


@dataclass(frozen=True, slots=True, kw_only=True)
class Device(Stream):
    """A motor-like device assembled in-process from its EPICS log streams.

    ``DeviceSynthesizer`` watches the named substreams and emits a merged
    per-device record stream: ``value`` names the readback substream
    (required), ``target`` the setpoint, ``idle`` the motion-done flag.
    All three are *names* (keys produced by :func:`name_streams`), not
    paths — a Device is wired after naming, so it survives renames of the
    underlying NeXus groups.
    """

    value: str
    target: str | None = None
    idle: str | None = None
    units: str | None = None
    writer_module: str = "device"
    nx_class: str = "NXpositioner"

    @property
    def substream_names(self) -> tuple[str, ...]:
        return tuple(
            n for n in (self.value, self.target, self.idle) if n is not None
        )


@dataclass(frozen=True, slots=True, kw_only=True)
class ContextBinding:
    """Routes one stream's latest value into workflows as named context.

    Workflows in this framework are jitted step functions taking named
    context scalars, so ``workflow_key`` is a plain string (the reference
    binds sciline graph keys here instead). Jobs for any source in
    ``dependent_sources`` hold in ``pending_context`` until the stream has
    delivered at least one value. Bindings live in their own list on the
    instrument — usage of a stream is deliberately not a field of the
    stream itself.
    """

    stream_name: str
    workflow_key: str
    dependent_sources: frozenset[str]


#: Structural NeXus groups that carry no identity of their own; stripped
#: before deriving names so 'entry/instrument/wfm1/transformations/t1'
#: names as 'wfm1/t1'.
_GENERIC_GROUPS: frozenset[str] = frozenset(
    {"entry", "instrument", "sample", "sample_environment", "transformations"}
)


def suggest_names(
    paths: Iterable[str],
    *,
    min_depth: int = 2,
    forbidden: Iterable[str] | None = None,
) -> dict[str, str]:
    """Derive a unique short name for each NeXus group path.

    The name is the shortest tail (at least ``min_depth`` components) of
    the path with generic container groups removed, provided it is unique
    within the set and not in ``forbidden``. Ambiguous paths escalate to
    longer tails; as a last resort the full unfiltered path (unique by
    HDF5 construction) is used.
    """
    paths = list(paths)
    forbidden_set = frozenset(forbidden or ())
    full = {p: p.strip("/").split("/") for p in paths}
    filtered = {
        p: [c for c in full[p] if c not in _GENERIC_GROUPS] or full[p]
        for p in paths
    }

    result: dict[str, str] = {}
    pending = set(paths)
    for parts in (filtered, full):
        if not pending:
            break
        max_depth = max((len(parts[p]) for p in pending), default=1)
        depth = min_depth
        while pending and depth <= max(max_depth, min_depth):
            candidate = {
                p: "/".join(parts[p][-min(depth, len(parts[p])):])
                for p in pending
            }
            counts: dict[str, int] = {}
            for name in candidate.values():
                counts[name] = counts.get(name, 0) + 1
            still: set[str] = set()
            for path, name in candidate.items():
                if counts[name] == 1 and name not in forbidden_set:
                    result[path] = name
                else:
                    still.add(path)
            pending = still
            depth += 1
    return result


@dataclass(slots=True)
class _MotorParts:
    """Role slots accumulated while scanning one NeXus parent group.

    EPICS motor records expose their state as separate PVs whose names end
    in a role-identifying suffix; an f144 stream is slotted by that suffix
    of its Kafka source. A parent qualifies as a device once the readback
    slot is filled plus at least one of setpoint / motion-done.
    """

    readback: str | None = None  # <pv>.RBV
    setpoint: str | None = None  # <pv>.VAL
    moving_done: str | None = None  # <pv>.DMOV

    _SUFFIXES = (
        (".RBV", "readback"),
        (".VAL", "setpoint"),
        (".DMOV", "moving_done"),
    )

    def take(self, parent: str, path: str, source: str) -> None:
        for suffix, slot in self._SUFFIXES:
            if not source.endswith(suffix):
                continue
            if getattr(self, slot) is not None:
                raise ValueError(
                    f"motor group {parent!r}: {getattr(self, slot)!r} and "
                    f"{path!r} both end in {suffix} — ambiguous device"
                )
            setattr(self, slot, path)
            return

    @property
    def is_device(self) -> bool:
        return self.readback is not None and (
            self.setpoint is not None or self.moving_done is not None
        )


def _detect_devices(parsed: Mapping[str, Stream]) -> dict[str, _MotorParts]:
    """Find motor devices among the parsed f144 streams.

    Sibling f144 streams under one NeXus parent whose EPICS sources carry
    motor-record suffixes are grouped; qualifying groups become Devices in
    :func:`name_streams`. Readback/setpoint unit disagreement is a
    registry bug and raises.
    """
    groups: dict[str, _MotorParts] = {}
    for path, stream in parsed.items():
        if isinstance(stream, F144Stream) and stream.source is not None:
            parent = path.rsplit("/", 1)[0] if "/" in path else ""
            parts = groups.setdefault(parent, _MotorParts())
            parts.take(parent, path, stream.source)

    devices: dict[str, _MotorParts] = {}
    for parent, parts in groups.items():
        if not parts.is_device:
            continue
        if parts.setpoint is not None:
            rbv, val = parsed[parts.readback], parsed[parts.setpoint]
            ru = rbv.units if isinstance(rbv, F144Stream) else None
            vu = val.units if isinstance(val, F144Stream) else None
            if ru != vu:
                raise ValueError(
                    f"motor group {parent!r}: readback reports units {ru!r} "
                    f"but setpoint reports {vu!r}"
                )
        devices[parent] = parts
    return devices


#: f144 topics our PROD credentials may read. The facility ACL list is
#: incomplete, so authorization is granted per topic-family suffix, plus
#: the general data topic.
_READABLE_SUFFIXES: tuple[str, ...] = ("_choppers", "_motion", "_sample_env")
_READABLE_TOPICS: frozenset[str] = frozenset({"tn_data_general"})


def filter_authorized_streams(parsed: dict[str, Stream]) -> dict[str, Stream]:
    """Keep only streams readable under the production ACL grants."""

    def readable(s: Stream) -> bool:
        return s.topic is not None and (
            s.topic in _READABLE_TOPICS or s.topic.endswith(_READABLE_SUFFIXES)
        )

    return {path: s for path, s in parsed.items() if readable(s)}


def name_streams(
    parsed: dict[str, Stream],
    *,
    rename: dict[str, str] | None = None,
) -> dict[str, Stream]:
    """Turn a path-keyed parse result into the name-keyed stream registry.

    Names come from :func:`suggest_names` — substreams first (tails of at
    least two components), then detected device parents (one component,
    with all substream names forbidden so the two namespaces cannot
    collide). Entries in ``rename`` (keyed by NeXus path) win over
    suggestions. Detected motor groups are emitted as :class:`Device`
    records whose slots hold the *names* of their substreams.
    """
    rename = rename or {}
    devices = _detect_devices(parsed)
    nameable = set(parsed) | set(devices)
    if unknown := set(rename) - nameable:
        raise ValueError(
            f"rename targets nothing parsed or detected: {sorted(unknown)}"
        )
    sub_names = suggest_names(parsed.keys())
    parent_names = suggest_names(
        devices.keys(), min_depth=1, forbidden=sub_names.values()
    )
    chosen = {**sub_names, **parent_names, **rename}

    result: dict[str, Stream] = {}

    def place(path: str, stream: Stream) -> None:
        name = chosen[path]
        if name in result:
            raise ValueError(
                f"two streams both want the name {name!r} "
                f"(second is {path!r}) — disambiguate via rename"
            )
        result[name] = stream

    for path, stream in parsed.items():
        place(path, stream)
    for parent, parts in devices.items():
        rbv = parsed[parts.readback]
        place(
            parent,
            Device(
                nexus_path=parent,
                value=chosen[parts.readback],
                target=chosen[parts.setpoint] if parts.setpoint else None,
                idle=chosen[parts.moving_done] if parts.moving_done else None,
                units=rbv.units if isinstance(rbv, F144Stream) else None,
            ),
        )
    return result

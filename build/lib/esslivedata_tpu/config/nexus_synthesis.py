"""Synthesize instrument NeXus geometry artifacts.

The reference framework loads instrument geometry and its f144 stream
catalog from NeXus files fetched by a pooch registry
(reference: preprocessors/detector_data.py:66-127,
scripts/download_geometry.py, nexus_helpers.py). This environment has no
egress, so the artifacts are *synthesized* from declarative per-instrument
plans (``nexus_plans.py``) into files with the same structure real ESS
files have:

- NXdetector banks with ``detector_number``, pixel offsets and a
  ``NXevent_data`` group carrying ``topic``/``source``/``writer_module``
  attributes (the file-writer stream declaration convention);
- NXmonitor groups with ev44 event streams and motorised positioners;
- NXdisk_chopper groups with f144 rotation_speed/delay/phase logs;
- NXpositioner device groups whose ``value``/``target_value``/``idle_flag``
  NXlog children carry EPICS motor-record source suffixes
  (``.RBV``/``.VAL``/``.DMOV``) — the pattern ``stream.name_streams``
  detects and merges into synthesised Device streams;
- plain NXlog sample-environment / vacuum streams.

Everything downstream is identical to a real deployment: the stream
registry is *generated from the file* (``nexus_streams.py``), geometry is
*loaded from the file* (``geometry_store.py``), and swapping in a real ESS
artifact requires no code change.
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path

import numpy as np

__all__ = [
    "BankPlan",
    "ChopperPlan",
    "DevicePlan",
    "InstrumentNexusPlan",
    "LogPlan",
    "MonitorPlan",
    "write_nexus",
]


@dataclass(frozen=True)
class BankPlan:
    """One detector bank.

    ``logical=False``: a rectangular (or cylinder-mantle) geometric bank —
    ``shape`` is (ny, nx) and pixel offsets are written.
    ``logical=True``: an N-d logical bank (DREAM/BIFROST style) — ``shape``
    may have any rank, only ``detector_number`` is written (named axes live
    in the instrument's view specs, not the file).
    """

    name: str  # NeXus group name, e.g. 'larmor_detector'
    source: str  # ev44 source name on the wire
    topic: str
    shape: tuple[int, ...]  # (ny, nx) pixels, or N-d for logical banks
    extent: tuple[float, float] = (1.0, 1.0)  # (height, width) metres
    z: float = 5.0  # sample->bank distance along beam, metres
    first_id: int = 1
    curvature_radius: float | None = None  # cylinder mantle around z axis
    logical: bool = False


@dataclass(frozen=True)
class MonitorPlan:
    name: str
    source: str
    topic: str
    z: float = -2.0
    positioner_pv: str | None = None  # adds a motorised monitor_positioner
    positioner_topic: str | None = None


@dataclass(frozen=True)
class ChopperPlan:
    name: str
    pv: str  # PV base, e.g. 'LOKI:Chop:BWC1'
    topic: str
    speed_hz: float = 14.0


@dataclass(frozen=True)
class DevicePlan:
    """A motorised axis: one NXpositioner with RBV/VAL/DMOV NXlog children."""

    group: str  # slash path under /entry/instrument, e.g. 'sample_stage/x'
    pv: str  # EPICS motor record base; .RBV/.VAL/.DMOV appended
    topic: str
    units: str = "mm"
    with_idle: bool = True
    with_target: bool = True


@dataclass(frozen=True)
class LogPlan:
    """A plain f144 log stream (sample environment, vacuum, ...)."""

    group: str  # slash path under /entry, e.g. 'sample/temperature'
    source: str
    topic: str
    units: str = ""


@dataclass(frozen=True)
class InstrumentNexusPlan:
    name: str
    title: str = ""
    banks: tuple[BankPlan, ...] = ()
    monitors: tuple[MonitorPlan, ...] = ()
    choppers: tuple[ChopperPlan, ...] = ()
    devices: tuple[DevicePlan, ...] = ()
    logs: tuple[LogPlan, ...] = ()

    def f144_stream_count(self) -> int:
        """Number of f144 declarations the built file will contain."""
        n = len(self.logs) + 4 * len(self.choppers)
        for d in self.devices:
            n += 1 + int(d.with_target) + int(d.with_idle)
        for m in self.monitors:
            if m.positioner_pv is not None:
                n += 3
        return n


# -- HDF5 writing -----------------------------------------------------------


def _group(parent, name: str, nx_class: str):
    g = parent.require_group(name)
    g.attrs["NX_class"] = nx_class
    return g


def _stream_group(
    parent,
    name: str,
    *,
    nx_class: str,
    writer_module: str,
    topic: str,
    source: str,
    units: str | None = None,
):
    """A NeXus group declaring a Kafka stream (file-writer convention:
    ``topic``/``source``/``writer_module`` attributes on the group)."""
    g = _group(parent, name, nx_class)
    g.attrs["topic"] = topic
    g.attrs["source"] = source
    g.attrs["writer_module"] = writer_module
    if units:
        g.attrs["units"] = units
    if writer_module == "f144":
        # Empty value/time shells: real files have the streamed history;
        # geometry artifacts are truncated to length 0 (same convention as
        # scripts/make_geometry_nexus.py).
        g.create_dataset("time", shape=(0,), dtype="i8")
        v = g.create_dataset("value", shape=(0,), dtype="f8")
        if units:
            v.attrs["units"] = units
    return g


def _write_bank(instr, plan: BankPlan) -> None:
    det = _group(instr, plan.name, "NXdetector")
    n = int(np.prod(plan.shape))
    # Large layouts (NMX panels are 1280x1280) compress ~100x as aranges;
    # shuffle+gzip keeps multi-megapixel artifacts small on disk.
    opts = (
        {"compression": "gzip", "shuffle": True} if n > (1 << 18) else {}
    )
    det.create_dataset(
        "detector_number",
        data=np.arange(plan.first_id, plan.first_id + n, dtype=np.int32).reshape(
            plan.shape
        ),
        **opts,
    )
    if plan.logical:
        _stream_group(
            det,
            f"{plan.name}_events",
            nx_class="NXevent_data",
            writer_module="ev44",
            topic=plan.topic,
            source=plan.source,
        )
        return
    ny, nx = plan.shape
    h, w = plan.extent
    ys = np.linspace(-h / 2, h / 2, ny)
    if plan.curvature_radius is None:
        xs = np.linspace(-w / 2, w / 2, nx)
        gx, gy = np.meshgrid(xs, ys)
        gz = np.full_like(gx, plan.z)
    else:
        # Mantle: pixels on a cylinder of given radius around the z axis.
        r = plan.curvature_radius
        phi = np.linspace(-w / (2 * r), w / (2 * r), nx)
        gphi, gy = np.meshgrid(phi, ys)
        gx = r * np.sin(gphi)
        gz = plan.z + r * (np.cos(gphi) - 1.0)
    for dsname, grid in (
        ("x_pixel_offset", gx),
        ("y_pixel_offset", gy),
        ("z_pixel_offset", gz),
    ):
        d = det.create_dataset(dsname, data=grid.astype(np.float64), **opts)
        d.attrs["units"] = "m"
    _stream_group(
        det,
        f"{plan.name}_events",
        nx_class="NXevent_data",
        writer_module="ev44",
        topic=plan.topic,
        source=plan.source,
    )


def _write_positioner(
    parent, group_name: str, plan_pv: str, topic: str, units: str,
    with_target: bool = True, with_idle: bool = True,
) -> None:
    pos = _group(parent, group_name, "NXpositioner")
    _stream_group(
        pos,
        "value",
        nx_class="NXlog",
        writer_module="f144",
        topic=topic,
        source=f"{plan_pv}.RBV",
        units=units,
    )
    if with_target:
        _stream_group(
            pos,
            "target_value",
            nx_class="NXlog",
            writer_module="f144",
            topic=topic,
            source=f"{plan_pv}.VAL",
            units=units,
        )
    if with_idle:
        _stream_group(
            pos,
            "idle_flag",
            nx_class="NXlog",
            writer_module="f144",
            topic=topic,
            source=f"{plan_pv}.DMOV",
            units="dimensionless",
        )


def write_nexus(plan: InstrumentNexusPlan, path: str | Path) -> Path:
    """Build the instrument's NeXus geometry artifact at ``path``."""
    import h5py

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with h5py.File(path, "w") as f:
        entry = _group(f, "entry", "NXentry")
        entry.create_dataset("title", data=plan.title or plan.name)
        instr = _group(entry, "instrument", "NXinstrument")
        instr.create_dataset("name", data=plan.name.upper())

        for bank in plan.banks:
            _write_bank(instr, bank)

        for mon in plan.monitors:
            g = _group(instr, mon.name, "NXmonitor")
            _stream_group(
                g,
                f"{mon.name}_events",
                nx_class="NXevent_data",
                writer_module="ev44",
                topic=mon.topic,
                source=mon.source,
            )
            d = g.create_dataset("distance", data=np.float64(mon.z))
            d.attrs["units"] = "m"
            if mon.positioner_pv is not None:
                _write_positioner(
                    g,
                    "monitor_positioner",
                    mon.positioner_pv,
                    mon.positioner_topic or mon.topic,
                    "mm",
                )

        for ch in plan.choppers:
            g = _group(instr, ch.name, "NXdisk_chopper")
            d = g.create_dataset(
                "nominal_speed", data=np.float64(ch.speed_hz)
            )
            d.attrs["units"] = "Hz"
            # Source suffixes follow config/chopper.py's PV convention
            # (':SpdSet' setpoint, ':Delay' readback): instruments that
            # hand-declare chopper streams via chopper_pv_streams get
            # *identical* parsed entries, which the catalog merge refines
            # (adds nexus_path) instead of rejecting.
            for group_name, suffix, units in (
                ("rotation_speed_setpoint", "SpdSet", "Hz"),
                ("rotation_speed", "Spd", "Hz"),
                ("delay", "Delay", "ns"),
                ("phase", "Phs", "deg"),
            ):
                _stream_group(
                    g,
                    group_name,
                    nx_class="NXlog",
                    writer_module="f144",
                    topic=ch.topic,
                    source=f"{ch.pv}:{suffix}",
                    units=units,
                )

        for dev in plan.devices:
            *parents, leaf = dev.group.split("/")
            node = instr
            for p in parents:
                node = _group(node, p, "NXcollection")
            _write_positioner(
                node,
                leaf,
                dev.pv,
                dev.topic,
                dev.units,
                with_target=dev.with_target,
                with_idle=dev.with_idle,
            )

        for log in plan.logs:
            *parents, leaf = log.group.split("/")
            node = entry
            for p in parents:
                node = _group(node, p, "NXcollection")
            _stream_group(
                node,
                leaf,
                nx_class="NXlog",
                writer_module="f144",
                topic=log.topic,
                source=log.source,
                units=log.units,
            )
    return path

"""The command/identity vocabulary shared by services, dashboard and wire.

Parity with reference ``config/workflow_spec.py`` (WorkflowSpec:312,
WorkflowId:146, JobId:179, JobSchedule:519, WorkflowConfig:551,
ResultKey:275, OutputView:43): pydantic models so that (a) commands
round-trip JSON on the Kafka commands topic and (b) params models *are* the
dashboard's auto-generated UI schema. Output templates are empty labeled
DataArrays that drive plotter auto-selection (reference :366-383).
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Literal

from pydantic import BaseModel, ConfigDict, Field, field_validator

from ..core.timestamp import Timestamp
from ..utils.labeled import DataArray

__all__ = [
    "JobId",
    "JobSchedule",
    "OutputSpec",
    "ResultKey",
    "WorkflowConfig",
    "WorkflowId",
    "WorkflowSpec",
]


_ID_FORBIDDEN = set("|/")


def _check_id_field(value: str) -> str:
    if not value or _ID_FORBIDDEN & set(value):
        raise ValueError(
            f"Identifier field {value!r} must be non-empty and contain no "
            "'|' or '/' (reserved for the ResultKey wire encoding)"
        )
    return value


def _check_pipe_free(value: str) -> str:
    """Source/output names ride as single '|'-separated ResultKey fields, so
    only '|' is reserved; '/' is allowed (catalog stream names follow the
    NeXus path convention, e.g. 'c1/delay_setpoint', 'motor/value')."""
    if not value or "|" in value:
        raise ValueError(
            f"Name {value!r} must be non-empty and contain no '|' "
            "(reserved for the ResultKey wire encoding)"
        )
    return value


class WorkflowId(BaseModel):
    """Identifies a workflow implementation (not an instance)."""

    model_config = ConfigDict(frozen=True)

    instrument: str
    namespace: str = "default"
    name: str
    version: int = 1

    @field_validator("instrument", "namespace", "name")
    @classmethod
    def _safe_fields(cls, v: str) -> str:
        return _check_id_field(v)

    def __str__(self) -> str:
        return f"{self.instrument}/{self.namespace}/{self.name}/v{self.version}"

    @classmethod
    def parse(cls, s: str) -> WorkflowId:
        instrument, namespace, name, v = s.split("/")
        return cls(
            instrument=instrument,
            namespace=namespace,
            name=name,
            version=int(v.lstrip("v")),
        )


class JobId(BaseModel):
    """One running workflow instance bound to one source."""

    model_config = ConfigDict(frozen=True)

    source_name: str
    job_number: uuid.UUID = Field(default_factory=uuid.uuid4)

    @field_validator("source_name")
    @classmethod
    def _safe_source(cls, v: str) -> str:
        return _check_pipe_free(v)

    def __str__(self) -> str:
        return f"{self.source_name}:{self.job_number}"


class JobSchedule(BaseModel):
    """Data-time activation window (ns epoch); None = immediately/forever.

    Jobs activate when *data time* reaches start_time and finish when it
    passes end_time — never wall clock (reference job_manager.py:357)."""

    model_config = ConfigDict(frozen=True)

    start_time_ns: int | None = None
    end_time_ns: int | None = None

    @property
    def start(self) -> Timestamp | None:
        return None if self.start_time_ns is None else Timestamp(self.start_time_ns)

    @property
    def end(self) -> Timestamp | None:
        return None if self.end_time_ns is None else Timestamp(self.end_time_ns)


class WorkflowConfig(BaseModel):
    """The start-job command as it travels the commands topic."""

    identifier: WorkflowId
    job_id: JobId
    params: dict[str, Any] = Field(default_factory=dict)
    aux_source_names: dict[str, str] = Field(default_factory=dict)
    schedule: JobSchedule = Field(default_factory=JobSchedule)


class ResultKey(BaseModel):
    """Routing key stamped on every published result. Travels compactly as
    the da00 source_name so the dashboard can route without extra headers."""

    model_config = ConfigDict(frozen=True)

    workflow_id: WorkflowId
    job_id: JobId
    output_name: str

    @field_validator("output_name")
    @classmethod
    def _safe_output(cls, v: str) -> str:
        return _check_pipe_free(v)

    def to_string(self) -> str:
        return (
            f"{self.workflow_id}|{self.job_id.source_name}"
            f"|{self.job_id.job_number}|{self.output_name}"
        )

    @classmethod
    def from_string(cls, s: str) -> ResultKey:
        wid, source, job_number, output = s.split("|")
        return cls(
            workflow_id=WorkflowId.parse(wid),
            job_id=JobId(source_name=source, job_number=uuid.UUID(job_number)),
            output_name=output,
        )


class OutputSpec(BaseModel):
    """Declares one named workflow output.

    ``template`` produces an empty DataArray with the output's dims, units
    and coords — the dashboard selects plotters from it without running the
    workflow (reference workflow_spec.py:366-383). ``view`` distinguishes
    per-update (window) from since-start (cumulative) outputs.
    """

    model_config = ConfigDict(arbitrary_types_allowed=True)

    title: str = ""
    description: str = ""
    view: Literal["per_update", "since_start"] = "per_update"
    template: Callable[[], DataArray] | None = None


class WorkflowSpec(BaseModel):
    """Declarative description of a workflow: what it consumes, its
    parameter schema, and the outputs it produces."""

    model_config = ConfigDict(arbitrary_types_allowed=True)

    instrument: str
    namespace: str = "default"
    name: str
    version: int = 1
    title: str = ""
    description: str = ""
    source_names: list[str] = Field(default_factory=list)
    aux_source_names: dict[str, list[str]] = Field(default_factory=dict)
    params_model: type[BaseModel] | None = None
    outputs: dict[str, OutputSpec] = Field(default_factory=dict)
    # output_name -> NICOS device-name template; ``{source_name}`` is the
    # only placeholder. Outputs listed here are republished on the stable
    # NICOS device topic (reference workflow_spec.py device_outputs, ADR 0006).
    device_outputs: dict[str, str] = Field(default_factory=dict)
    context_keys: list[str] = Field(default_factory=list)
    #: Context streams delivered WHEN AVAILABLE but never gated on —
    #: live calibrations with a static-param fallback (e.g. the powder
    #: emission offset). Gating keys above hold the job until a value
    #: exists; optional keys must not strand jobs in deployments where
    #: the stream is not produced.
    optional_context_keys: list[str] = Field(default_factory=list)
    reset_on_run_transition: bool = True
    service: str | None = None
    """Backend service hosting this spec (detector_data/monitor_data/
    data_reduction/timeseries). None = derive from the namespace
    (route_derivation.spec_service); display grouping and hosting service
    are decoupled, as in the reference's per-registration service field."""

    @field_validator("source_names")
    @classmethod
    def _nonempty_names(cls, v: list[str]) -> list[str]:
        if any(not s for s in v):
            raise ValueError("source names must be non-empty")
        return v

    @property
    def identifier(self) -> WorkflowId:
        return WorkflowId(
            instrument=self.instrument,
            namespace=self.namespace,
            name=self.name,
            version=self.version,
        )

    def validate_params(self, params: dict[str, Any]) -> BaseModel | None:
        """Parse raw command params through this spec's model."""
        if self.params_model is None:
            if params:
                raise ValueError(
                    f"Workflow {self.identifier} accepts no params, got {params}"
                )
            return None
        return self.params_model.model_validate(params)

"""Topic naming + per-instrument stream mapping construction.

Parity with reference ``config/streams.py`` (stream_kind_to_topic:20,
get_stream_mapping:54): raw topics follow the facility convention
``{instrument}_detector|_monitor|_camera|_motion|_runInfo``; our own
output/control topics are the ``{instrument}_livedata_*`` family
(kafka/stream_mapping.LivedataTopics). DEV mode prefixes topics so a dev
broker can coexist with production.
"""

from __future__ import annotations

from ..core.message import StreamKind
from ..kafka.stream_mapping import InputStreamKey, StreamMapping
from .instrument import Instrument

__all__ = ["get_stream_mapping", "stream_kind_to_topic"]


def stream_kind_to_topic(instrument: str, kind: StreamKind, dev: bool = False) -> str:
    prefix = f"dev_{instrument}" if dev else instrument
    suffix = {
        StreamKind.DETECTOR_EVENTS: "detector",
        StreamKind.MONITOR_EVENTS: "monitor",
        StreamKind.MONITOR_COUNTS: "monitor",
        StreamKind.AREA_DETECTOR: "camera",
        StreamKind.LOG: "motion",
        StreamKind.DEVICE: "motion",
        StreamKind.RUN_CONTROL: "runInfo",
    }.get(kind)
    if suffix is None:
        raise ValueError(f"No raw topic for stream kind {kind}")
    return f"{prefix}_{suffix}"


def get_stream_mapping(instrument: Instrument, dev: bool = False) -> StreamMapping:
    name = instrument.name
    det_topic = stream_kind_to_topic(name, StreamKind.DETECTOR_EVENTS, dev)
    mon_topic = stream_kind_to_topic(name, StreamKind.MONITOR_EVENTS, dev)
    cam_topic = stream_kind_to_topic(name, StreamKind.AREA_DETECTOR, dev)
    log_topic = stream_kind_to_topic(name, StreamKind.LOG, dev)
    run_topic = stream_kind_to_topic(name, StreamKind.RUN_CONTROL, dev)
    return StreamMapping(
        dev=dev,
        instrument=name,
        detectors={
            InputStreamKey(topic=det_topic, source_name=d.source_name): d.name
            for d in instrument.detectors.values()
        },
        monitors={
            InputStreamKey(topic=mon_topic, source_name=m.source_name): m.name
            for m in instrument.monitors.values()
        },
        pixellated_monitors=frozenset(instrument.pixellated_monitor_names),
        area_detectors={
            InputStreamKey(topic=cam_topic, source_name=c.source_name): c.name
            for c in instrument.cameras.values()
        },
        logs=_build_logs_lut(instrument, log_topic, dev),
        run_control_topics=(run_topic,),
    )


def _build_logs_lut(
    instrument: Instrument, log_topic: str, dev: bool
) -> dict[InputStreamKey, str]:
    """Merge log_sources (convention topic) with catalog streams (declared
    topics). Catalog topics get the same dev prefix as convention topics so
    a dev broker never shadows or consumes production streams; synthesised
    catalog entries (topic None) never ride Kafka and stay out of the LUT.
    Duplicate (topic, source) keys are a misconfiguration and raise."""
    lut: dict[InputStreamKey, str] = {
        InputStreamKey(topic=log_topic, source_name=source): stream
        for stream, source in instrument.log_sources.items()
    }
    for stream_name, s in instrument.streams.items():
        if s.topic is None or s.source is None:
            continue
        topic = f"dev_{s.topic}" if dev else s.topic
        key = InputStreamKey(topic=topic, source_name=s.source)
        if key in lut:
            raise ValueError(
                f"Stream {stream_name!r} and {lut[key]!r} both claim "
                f"(topic={key.topic!r}, source={key.source_name!r})"
            )
        lut[key] = stream_name
    return lut

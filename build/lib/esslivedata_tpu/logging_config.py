"""Structured logging configuration (reference: logging_config.py).

The reference uses structlog (console or JSON-file output); structlog is
not available here, so this builds the same surface on stdlib logging: a
key=value console formatter and a rotating JSON-lines file handler (10 MB
x 5 backups), both rendering ``extra``-style structured fields. Services
pass ``--log-json-file`` through to ``json_file``.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from logging.handlers import RotatingFileHandler

__all__ = ["configure_logging"]

#: LogRecord attributes that are stdlib plumbing, not user fields.
_RESERVED = frozenset(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime", "taskName"}


def _extra_fields(record: logging.LogRecord) -> dict:
    return {
        k: v for k, v in record.__dict__.items() if k not in _RESERVED
    }


class KeyValueFormatter(logging.Formatter):
    """``2026-07-29T12:00:00 INFO  name  message key=value ...``"""

    def format(self, record: logging.LogRecord) -> str:
        stamp = time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.localtime(record.created)
        )
        base = (
            f"{stamp} {record.levelname:<7} {record.name}  "
            f"{record.getMessage()}"
        )
        fields = _extra_fields(record)
        if fields:
            base += "  " + " ".join(
                f"{k}={v!r}" for k, v in sorted(fields.items())
            )
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


class JsonLinesFormatter(logging.Formatter):
    """One JSON object per line: machine-ingestible service logs."""

    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "timestamp": time.strftime(
                "%Y-%m-%dT%H:%M:%S%z", time.localtime(record.created)
            ),
            "level": record.levelname.lower(),
            "logger": record.name,
            "event": record.getMessage(),
            **_extra_fields(record),
        }
        if record.exc_info:
            payload["exception"] = self.formatException(record.exc_info)
        return json.dumps(payload, default=repr)


def configure_logging(
    *,
    level: int | str = logging.INFO,
    json_file: str | None = None,
    disable_stdout: bool = False,
) -> None:
    """Configure root logging: pretty console and/or rotating JSON file.

    ``level`` accepts a name ('info', 'DEBUG') or a numeric level — CLI
    entry points pass their --log-level string straight through.
    """
    if isinstance(level, str):
        level = getattr(logging, level.upper(), logging.INFO)
    root = logging.getLogger()
    for handler in root.handlers:
        handler.close()  # release file descriptors on reconfiguration
    root.handlers.clear()
    if not disable_stdout:
        console = logging.StreamHandler(sys.stdout)
        console.setFormatter(KeyValueFormatter())
        root.addHandler(console)
    if json_file is not None:
        file_handler = RotatingFileHandler(
            json_file, maxBytes=10 * 1024 * 1024, backupCount=5
        )
        file_handler.setFormatter(JsonLinesFormatter())
        root.addHandler(file_handler)
    root.setLevel(level)

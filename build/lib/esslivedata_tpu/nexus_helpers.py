"""NeXus file introspection helpers (reference: nexus_helpers.py).

Host-side, cold-path utilities over h5py: discover streamed groups (the
input to the generated stream registries, ADR 0009), and load detector
geometry — pixel positions resolved through ``depends_on`` transformation
chains — for building projection tables.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = [
    "StreamedGroup",
    "find_streamed_groups",
    "load_detector_geometry",
    "resolve_depends_on",
]


@dataclass(frozen=True)
class StreamedGroup:
    """One group a filewriter streams from Kafka (NXlog / NXevent_data)."""

    nexus_path: str
    nx_class: str
    topic: str | None
    source: str | None
    units: str | None


def _attr(obj, name: str) -> str | None:
    value = obj.attrs.get(name)
    if value is None:
        return None
    if isinstance(value, bytes):
        return value.decode()
    return str(value)


def find_streamed_groups(filename: str) -> list[StreamedGroup]:
    """All NXlog/NXevent_data groups with their stream identity.

    Topic/source conventions follow the ESS filewriter: groups carry
    ``topic``/``source`` attrs (or a child dataset of those names holding
    the strings).
    """
    import h5py

    out: list[StreamedGroup] = []

    def visit(path: str, obj) -> None:
        if not isinstance(obj, h5py.Group):
            return
        nx_class = _attr(obj, "NX_class")
        if nx_class not in ("NXlog", "NXevent_data"):
            return

        def get(name: str) -> str | None:
            if (value := _attr(obj, name)) is not None:
                return value
            child = obj.get(name)
            if isinstance(child, h5py.Dataset):
                raw = child[()]
                return raw.decode() if isinstance(raw, bytes) else str(raw)
            return None

        units = None
        value_ds = obj.get("value")
        if isinstance(value_ds, h5py.Dataset):
            units = _attr(value_ds, "units")
        out.append(
            StreamedGroup(
                nexus_path=path,
                nx_class=nx_class,
                topic=get("topic"),
                source=get("source"),
                units=units,
            )
        )

    with h5py.File(filename, "r") as f:
        f.visititems(visit)
    return out


def _transform_matrix(node) -> np.ndarray:
    """4x4 matrix for one NXtransformations entry (value + attrs).

    ``node`` may be a dataset or an NXlog *group* (motion-controlled
    transform): for a group the samples come from its ``value`` dataset
    while transformation attrs are looked up on the group first, then the
    dataset. An empty value — the length-0 placeholder written by
    make_geometry_nexus.py — contributes magnitude 0 (identity modulo
    offset) so geometry artifacts load before any live motor value.
    """
    if hasattr(node, "keys") and "value" in node:  # NXlog group
        group, dataset = node, node["value"]
    else:
        group, dataset = None, node

    def attr(name: str, default=None):
        for host in (group, dataset):
            if host is not None and name in host.attrs:
                return host.attrs[name]
        return default

    raw = np.atleast_1d(dataset[()])
    value = float(raw[-1]) if raw.size else 0.0
    kind = attr("transformation_type")
    if isinstance(kind, bytes):
        kind = kind.decode()
    vector = np.asarray(attr("vector", (0.0, 0.0, 1.0)), dtype=float)
    norm = np.linalg.norm(vector)
    vector = vector / norm if norm else vector
    offset = np.asarray(attr("offset", (0.0, 0.0, 0.0)), dtype=float)
    m = np.eye(4)
    if kind == "translation":
        m[:3, 3] = vector * value
    elif kind == "rotation":
        theta = np.deg2rad(value)
        k = np.array(
            [
                [0, -vector[2], vector[1]],
                [vector[2], 0, -vector[0]],
                [-vector[1], vector[0], 0],
            ]
        )
        m[:3, :3] = (
            np.eye(3) + np.sin(theta) * k + (1 - np.cos(theta)) * (k @ k)
        )
    m[:3, 3] += offset
    return m


def resolve_depends_on(f, start: str, *, base: str = "") -> np.ndarray:
    """Compose the depends_on chain starting at ``start`` into one 4x4
    matrix (root-most applied last, per the NeXus spec).

    A relative ``start`` (no leading '/') resolves against ``base`` — the
    group that declared it — matching the NeXus relative-target rule.
    """
    m = np.eye(4)
    if not start.startswith("/") and base:
        start = f"{base.rstrip('/')}/{start}"
    path = start
    seen: set[str] = set()
    while path and path != ".":
        if path in seen:
            raise ValueError(f"depends_on cycle at {path!r}")
        seen.add(path)
        dataset = f[path]
        m = _transform_matrix(dataset) @ m
        nxt = _attr(dataset, "depends_on")
        if nxt is None or nxt == ".":
            break
        path = nxt if nxt.startswith("/") else f"{path.rsplit('/', 1)[0]}/{nxt}"
    return m


def load_detector_geometry(
    filename: str, detector_path: str
) -> tuple[np.ndarray, np.ndarray]:
    """(positions [n,3], detector_number [n]) for one NXdetector group.

    Pixel offsets (x/y/z_pixel_offset) are broadcast to the detector_number
    shape and pushed through the group's depends_on chain.
    """
    import h5py

    with h5py.File(filename, "r") as f:
        group = f[detector_path]
        det = np.asarray(group["detector_number"][()])
        shape = det.shape

        def offsets(name: str) -> np.ndarray:
            if name in group:
                return np.broadcast_to(
                    np.asarray(group[name][()], dtype=float), shape
                ).reshape(-1)
            return np.zeros(det.size)

        local = np.column_stack(
            [
                offsets("x_pixel_offset"),
                offsets("y_pixel_offset"),
                offsets("z_pixel_offset"),
            ]
        )
        depends = group.get("depends_on")
        if isinstance(depends, h5py.Dataset):
            target = depends[()]
            target = target.decode() if isinstance(target, bytes) else target
            if target and target != ".":
                m = resolve_depends_on(f, target, base=detector_path)
                local = local @ m[:3, :3].T + m[:3, 3]
    return local, det.reshape(-1)

"""Typed nanosecond-epoch timestamps and durations.

Semantics follow the reference's ``core/timestamp.py`` (unit-checked
arithmetic, pulse-grid quantization, reference: timestamp.py:140,224-232) but
the implementation is pure-integer: the reference converts through scipp unit
machinery; here a Timestamp is an int64-range ns count and the 14 Hz pulse
grid is handled with exact rational arithmetic (period = 10^9/14 ns), so
``quantize``/``quantize_up`` are reproducible and drift-free over any epoch.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

from .constants import PULSE_PERIOD_NS_DEN, PULSE_PERIOD_NS_NUM

__all__ = ["Duration", "Timestamp"]

_UNIT_NS: dict[str, int] = {"ns": 1, "us": 1_000, "ms": 1_000_000, "s": 1_000_000_000}


def _to_ns(value: float | int, unit: str) -> int:
    try:
        factor = _UNIT_NS[unit]
    except KeyError as err:
        raise ValueError(f"Unsupported time unit {unit!r}") from err
    return round(value * factor)


@dataclass(frozen=True, slots=True, order=True)
class Duration:
    """A length of time in integer nanoseconds."""

    ns: int

    @classmethod
    def from_value(cls, value: float, unit: str = "s") -> Duration:
        return cls(_to_ns(value, unit))

    @classmethod
    def from_s(cls, seconds: float) -> Duration:
        return cls(_to_ns(seconds, "s"))

    @classmethod
    def from_ms(cls, ms: float) -> Duration:
        return cls(_to_ns(ms, "ms"))

    @classmethod
    def from_ns(cls, ns: int) -> Duration:
        return cls(int(ns))

    @property
    def seconds(self) -> float:
        return self.ns / 1e9

    def __add__(self, other: Duration) -> Duration:
        if not isinstance(other, Duration):
            return NotImplemented
        return Duration(self.ns + other.ns)

    def __sub__(self, other: Duration) -> Duration:
        if not isinstance(other, Duration):
            return NotImplemented
        return Duration(self.ns - other.ns)

    def __mul__(self, factor: float) -> Duration:
        return Duration(round(self.ns * factor))

    __rmul__ = __mul__

    def __truediv__(self, other: Duration | float) -> float | Duration:
        if isinstance(other, Duration):
            return self.ns / other.ns
        return Duration(round(self.ns / other))

    def __neg__(self) -> Duration:
        return Duration(-self.ns)

    def __bool__(self) -> bool:
        return self.ns != 0

    def __str__(self) -> str:
        return f"{self.seconds:g}s"


@dataclass(frozen=True, slots=True, order=True)
class Timestamp:
    """Nanoseconds since the Unix epoch (UTC). The data-time clock of the
    whole system: batching windows and job schedules compare these, never
    wall-clock (reference: core/message_batcher.py data-derived clock)."""

    ns: int

    @classmethod
    def from_ns(cls, ns: int) -> Timestamp:
        return cls(int(ns))

    @classmethod
    def from_value(cls, value: float, unit: str = "s") -> Timestamp:
        return cls(_to_ns(value, unit))

    @classmethod
    def now(cls) -> Timestamp:
        return cls(time.time_ns())

    @property
    def seconds(self) -> float:
        return self.ns / 1e9

    # -- arithmetic (unit-checked by type) --------------------------------
    def __add__(self, other: Duration) -> Timestamp:
        if not isinstance(other, Duration):
            return NotImplemented
        return Timestamp(self.ns + other.ns)

    def __radd__(self, other: Duration) -> Timestamp:
        return self.__add__(other)

    def __sub__(self, other: Timestamp | Duration) -> Any:
        if isinstance(other, Timestamp):
            return Duration(self.ns - other.ns)
        if isinstance(other, Duration):
            return Timestamp(self.ns - other.ns)
        return NotImplemented

    # -- pulse grid -------------------------------------------------------
    def pulse_index(self) -> int:
        """Index of the source pulse containing this time (floor)."""
        return (self.ns * PULSE_PERIOD_NS_DEN) // PULSE_PERIOD_NS_NUM

    @classmethod
    def from_pulse_index(cls, index: int) -> Timestamp:
        # Ceiling division: the smallest ns time whose pulse_index is
        # ``index``, so pulse_index(from_pulse_index(i)) == i exactly.
        return cls(-((-index * PULSE_PERIOD_NS_NUM) // PULSE_PERIOD_NS_DEN))

    def quantize(self) -> Timestamp:
        """Round down onto the pulse grid (reference timestamp.py:224)."""
        return Timestamp.from_pulse_index(self.pulse_index())

    def quantize_up(self) -> Timestamp:
        """Round up onto the pulse grid (reference timestamp.py:232)."""
        q = self.quantize()
        if q == self:
            return q
        return Timestamp.from_pulse_index(self.pulse_index() + 1)

    def __str__(self) -> str:
        return f"t={self.seconds:.6f}s"

"""In-memory message source/sink doubles (reference: fakes.py:11,28)."""

from __future__ import annotations

from collections.abc import Sequence

from .message import Message

__all__ = ["FakeMessageSink", "FakeMessageSource"]


class FakeMessageSource:
    """Yields pre-loaded message batches, one batch per ``get_messages``."""

    def __init__(self, messages: Sequence[Sequence[Message]] = ()) -> None:
        self._batches: list[list[Message]] = [list(b) for b in messages]
        self._index = 0

    def append(self, batch: Sequence[Message]) -> None:
        self._batches.append(list(batch))

    def get_messages(self) -> list[Message]:
        if self._index >= len(self._batches):
            return []
        batch = self._batches[self._index]
        self._index += 1
        return batch

    @property
    def exhausted(self) -> bool:
        return self._index >= len(self._batches)


class FakeMessageSink:
    def __init__(self) -> None:
        self.messages: list[Message] = []

    def publish_messages(self, messages: Sequence[Message]) -> None:
        self.messages.extend(messages)

    def clear(self) -> None:
        self.messages.clear()

"""Core runtime: domain types, batchers, service loop, jobs, control plane.

Mirrors the responsibilities of the reference's ``src/ess/livedata/core/``
(SURVEY.md section 2.1) with the same protocol seams — MessageSource /
MessageSink / Processor / Accumulator / Workflow — so every layer above and
below can be faked in tests exactly like the reference does.
"""

from .message import (
    COMMAND_STREAM,
    RESPONSE_STREAM,
    RUN_CONTROL_STREAM,
    STATUS_STREAM,
    Message,
    MessageSink,
    MessageSource,
    RunStart,
    RunStop,
    StreamId,
    StreamKind,
)
from .timestamp import Duration, Timestamp

__all__ = [
    "COMMAND_STREAM",
    "Duration",
    "Message",
    "MessageSink",
    "MessageSource",
    "RESPONSE_STREAM",
    "RUN_CONTROL_STREAM",
    "RunStart",
    "RunStop",
    "STATUS_STREAM",
    "StreamId",
    "StreamKind",
    "Timestamp",
]

"""Accumulator + PreprocessorFactory protocols (reference: core/preprocessor.py:16,60).

An Accumulator ingests per-stream wire payloads between batch boundaries and
hands the accumulated value to workflows at window close. ``is_context``
marks accumulators whose value parameterizes workflows (motor positions,
chopper settings) rather than flowing as primary data (ADR 0002 semantics).
``release_buffers`` is the zero-copy contract: accumulators may hand out
views into internal buffers from ``get``; the runtime promises to call
``release_buffers`` after all jobs consumed them, before the next add cycle.
"""

from __future__ import annotations

from typing import Any, ClassVar, Protocol, TypeVar, runtime_checkable

from .message import StreamId
from .timestamp import Timestamp

T = TypeVar("T")
U = TypeVar("U")

__all__ = ["Accumulator", "PreprocessorFactory"]


@runtime_checkable
class Accumulator(Protocol[T, U]):
    is_context: ClassVar[bool] = False

    def add(self, timestamp: Timestamp, data: T) -> None: ...

    def get(self) -> U: ...

    def clear(self) -> None: ...

    def release_buffers(self) -> None: ...


@runtime_checkable
class PreprocessorFactory(Protocol):
    """Creates the right accumulator for a stream, or None to drop it."""

    def make_preprocessor(self, stream: StreamId) -> Any | None: ...

"""Processor protocol (reference: core/processor.py:14,30).

A processor owns its source and sink; ``process()`` runs one cycle of the
service loop. ``finalize()`` is called once at shutdown to flush state.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

from .message import MessageSink, MessageSource

__all__ = ["IdentityProcessor", "Processor"]


@runtime_checkable
class Processor(Protocol):
    def process(self) -> None: ...

    def finalize(self) -> None: ...


class IdentityProcessor:
    """Pass messages straight from source to sink — used by fake producers
    (reference: core/processor.py:30)."""

    def __init__(self, source: MessageSource, sink: MessageSink) -> None:
        self._source = source
        self._sink = sink

    def process(self) -> None:
        messages = self._source.get_messages()
        if messages:
            self._sink.publish_messages(list(messages))

    def finalize(self) -> None:
        pass

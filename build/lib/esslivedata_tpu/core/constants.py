"""Shared physical/operational constants (reference: core/constants.py:4)."""

PULSE_RATE_HZ = 14.0
"""ESS source pulse rate; the data-time grid all batching quantizes to."""

PULSE_PERIOD_NS_NUM = 10**9
PULSE_PERIOD_NS_DEN = 14
"""Pulse period as an exact rational (ns) to keep grid math integer-exact."""
